"""Shared CLI plumbing: config loading, flag surface, model assembly.

Mirrors the reference's OmegaConf-YAML + argparse surface
(/root/reference/run_tuning.py:398-425, run_videop2p.py:703-733) — the
reference's config files run unmodified — including the fork's output-dir
suffix mangling that carries the dependent-noise hyperparameters between
stages (run_tuning.py:97-99, run_videop2p.py:74-78).
"""

from __future__ import annotations

import argparse
import os
import warnings
from dataclasses import dataclass
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

__all__ = [
    "load_config",
    "add_dependent_args",
    "add_null_text_args",
    "add_obs_args",
    "dependent_suffix",
    "resolve_pipeline_dir",
    "build_models",
    "encode_prompts",
    "enable_compile_cache",
    "make_run_ledger",
    "setup_mesh",
    "ModelBundle",
]


def make_run_ledger(
    default_path: str,
    *,
    ledger: Optional[str] = None,
    mesh: Optional[str] = None,
    meta: Optional[Dict[str, Any]] = None,
    telemetry: bool = False,
    attn_maps: bool = False,
    quality: bool = False,
    report: bool = False,
    device_telemetry: bool = False,
    latency: bool = False,
    trace_analysis: bool = False,
    program_analysis: bool = True,
    enable: bool = False,
    set_latency_env: bool = True,
    incidents: Optional[str] = None,
):
    """The shared obs-flags → :class:`~videop2p_tpu.obs.RunLedger` wiring.

    Both CLIs, the serving engine and the load generator previously carried
    (or would have carried) near-identical copies of this block: decide
    whether any observability flag implies a ledger, resolve the default
    path, set the process-wide env knobs the pipeline-internal jits check,
    and ACTIVATE the ledger so ``phase_timer`` / the compile listener /
    ``instrumented_jit`` find it. Returns the activated ledger, or None
    when nothing asked for one. ``set_latency_env=False`` keeps ``--latency``
    scoped to this ledger's lifetime (long-lived in-process engines) instead
    of flipping the process-wide env var.
    """
    if not program_analysis:
        os.environ["VIDEOP2P_OBS_NO_ANALYSIS"] = "1"
    if not (enable or telemetry or ledger or attn_maps or quality or report
            or device_telemetry or latency or trace_analysis or incidents):
        return None
    if latency and set_latency_env:
        # pipeline-internal jits (the fused null-text cache) check the
        # env, not the wrapper — set it so their dispatches are timed too
        os.environ["VIDEOP2P_OBS_LATENCY"] = "1"
    from videop2p_tpu.obs import RunLedger

    base_meta = {
        "telemetry": bool(telemetry),
        "attn_maps": bool(attn_maps),
        "quality": bool(quality),
        "device_telemetry": bool(device_telemetry),
        "latency": bool(latency),
        "trace_analysis": bool(trace_analysis),
    }
    base_meta.update(meta or {})
    led = RunLedger(
        ledger or default_path, mesh=mesh, meta=base_meta, latency=latency
    ).activate()
    if incidents:
        # incident plane (ISSUE 18): the flight ring tees this ledger's
        # events, and crash/SIGUSR1 hooks capture bundles for the whole
        # CLI run — the manager rides the process lifetime (one-shot
        # CLIs), so no explicit close is threaded back
        from videop2p_tpu.obs.incident import IncidentManager

        mgr = IncidentManager(str(incidents), crash_hooks=True)
        mgr.attach_ledger(led)
        led.incidents = mgr
    return led


def enable_compile_cache(env_var: str = "VIDEOP2P_COMPILE_CACHE") -> None:
    """Persist compiled TPU executables across CLI invocations.

    The Stage-2 graph alone costs minutes of compile on a cold start (the
    round-3 CLI drive spent ~2 min in the first VAE decode, nearly all
    compile); a content-addressed on-disk cache makes every later run warm.
    Called at the binary boundary (the CLI entry points and bench.py) — a
    library import must not mutate global jax config. A cache dir configured
    earlier in the process (e.g. the test suite's conftest) wins: this is a
    default, not an override."""
    if jax.config.jax_compilation_cache_dir:
        return
    jax.config.update(
        "jax_compilation_cache_dir",
        os.environ.get(env_var,
                       os.path.expanduser("~/.cache/videop2p_jax_tpu_cache")),
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


def setup_mesh(bundle: "ModelBundle", mesh_spec: str, video_len: int,
               ring_variant: str = None, tp_collectives: str = None):
    """Parse a ``dp,sp,tp`` mesh spec and prepare the bundle for it: build
    the device mesh, wire ring attention into the UNet's uncontrolled
    temporal sites when frames are sharded, and shard the UNet params.
    Returns the mesh. Both CLIs share this; single-clip flows need dp=1.

    ``ring_variant`` picks the ring rotation schedule (``overlap`` — the
    double-buffered default — or ``bidir``/``serial``; None reads
    ``VIDEOP2P_RING_VARIANT``). ``tp_collectives="psum_scatter"`` wires the
    explicit Megatron reduce-scatter output seam on tensor-parallel meshes
    (None reads ``VIDEOP2P_TP_COLLECTIVES``, default ``gspmd`` —
    declarative)."""
    import os as _os

    from videop2p_tpu.parallel import (
        RING_VARIANTS,
        TP_COLLECTIVES,
        make_megatron_out_dot,
        make_mesh,
        make_ring_temporal_fn,
        make_sharded_frame_attention_fn,
        make_sharded_group_norm_fn,
        param_shardings,
    )

    if ring_variant is None:
        from videop2p_tpu.parallel import default_ring_variant

        ring_variant = default_ring_variant()
    if ring_variant not in RING_VARIANTS:
        raise ValueError(
            f"ring_variant must be one of {RING_VARIANTS}, got {ring_variant!r}"
        )
    if tp_collectives is None:
        tp_collectives = _os.environ.get(
            "VIDEOP2P_TP_COLLECTIVES", "gspmd"
        ).strip().lower()
    if tp_collectives not in TP_COLLECTIVES:
        raise ValueError(
            f"tp_collectives must be one of {TP_COLLECTIVES}, "
            f"got {tp_collectives!r}"
        )
    shape = tuple(int(t) for t in str(mesh_spec).split(","))
    if len(shape) != 3:
        raise ValueError(f"--mesh must be dp,sp,tp — got {mesh_spec!r}")
    dp, sp, tp = shape
    if dp != 1:
        raise ValueError(
            "single-clip flows run batch 1 — use dp=1 and put chips on the "
            f"frame/tensor axes, got dp={dp}"
        )
    if video_len % sp:
        raise ValueError(f"sp axis {sp} must divide video_len {video_len}")
    device_mesh = make_mesh(shape)
    print(f"[mesh] data={dp} frames={sp} tensor={tp}")
    if sp > 1 or tp > 1:
        # a model-internal axis is sharded: pjit cannot partition Pallas
        # custom calls, so the fused GroupNorm reaches the mesh through the
        # model's group_norm_fn seam instead of the naked kernel — the same
        # shard_map wrapper pattern as the sharded frame attention below.
        # Sites the wrapper does not cover (frame-pooled resnet slabs whose
        # statistics cross frame shards, slabs over the VMEM gate) fall
        # back to the two-pass XLA math GSPMD partitions as before.
        bundle.unet = bundle.unet.clone(
            group_norm_fn=make_sharded_group_norm_fn(
                device_mesh, impl=bundle.unet.config.group_norm
            )
        )
    if sp > 1:
        # ring attention on the uncontrolled temporal sites (training /
        # inversion; controlled sites stay dense for the P2P edit), and the
        # fused Pallas kernel on the sharded frame-attention sites via
        # shard_map (pjit alone cannot partition a Pallas custom call)
        bundle.unet = bundle.unet.clone(
            temporal_attention_fn=make_ring_temporal_fn(
                device_mesh, variant=ring_variant
            ),
            frame_attention_fn=make_sharded_frame_attention_fn(device_mesh),
        )
    if tp > 1 and tp_collectives == "psum_scatter":
        # explicit Megatron row-parallel outputs: reduce-scatter over the
        # token axis instead of the declarative all-reduce
        bundle.unet = bundle.unet.clone(
            row_parallel_dot=make_megatron_out_dot(device_mesh)
        )
    bundle.unet_params = jax.device_put(
        bundle.unet_params,
        param_shardings(device_mesh, bundle.unet_params, tensor_parallel=tp > 1),
    )
    return device_mesh


def load_config(path: str) -> Dict[str, Any]:
    import yaml

    with open(path) as f:
        return yaml.safe_load(f)


def add_dependent_args(parser: argparse.ArgumentParser) -> None:
    """The fork's flag surface (run_tuning.py:401-412, run_videop2p.py:708-720)."""
    parser.add_argument("--dependent", default=False, action="store_true")
    parser.add_argument("--ar_sample", default=False, action="store_true")
    parser.add_argument("--decay_rate", default=0.1, type=float)
    parser.add_argument("--window_size", default=60, type=int)
    parser.add_argument("--ar_coeff", default=0.1, type=float)
    parser.add_argument("--loss_sig", default=False, action="store_true")
    parser.add_argument("--num_frames", default=60, type=int)
    parser.add_argument("--eta", default=0.0, type=float)
    parser.add_argument("--dependent_weights", default=0.0, type=float)


def add_null_text_args(parser: argparse.ArgumentParser) -> None:
    """Official-mode null-text optimization knobs (pipelines/inversion.py)."""
    # defaults are None so a config-file value wins when the flag is unset
    # (the mixed_precision precedence pattern); the effective defaults live
    # on run_videop2p.main (fp32, chunk 0 = fused single dispatch)
    parser.add_argument(
        "--null_text_precision", type=str, default=None,
        choices=["fp32", "mixed"],
        help="null-text inner-loop precision: fp32 (default — reference "
             "behavior) or mixed — bf16 UNet forwards with fp32 "
             "scheduler/Adam/loss islands (~3-4x faster inner steps on "
             "TPU, reconstruction pinned within the fixed-work PSNR band)",
    )
    parser.add_argument(
        "--null_text_chunk", type=int, default=None,
        help="0 (default): run null-text optimization as ONE jitted device "
             "program with the trajectory buffer donated; N>0: split the "
             "outer scan into N-step host-dispatched chunks (the TPU "
             "execution-watchdog fallback for multi-minute fp32 programs)",
    )
    parser.add_argument(
        "--null_text_mode", type=str, default=None,
        choices=["optimize", "amortized", "hybrid"],
        help="how the per-step unconditional embedding is produced: "
             "optimize (default — the reference's per-step inner Adam "
             "loop), amortized (closed-form negative-prompt-inversion "
             "substitute: zero inner Adam steps, one forward per outer "
             "step — ~90%% of the official-mode wall-clock is this inner "
             "loop), or hybrid (amortized seed + <=3 refinement steps "
             "batched jointly across all outer steps). Reconstruction "
             "parity is pinned in tests and gated by the quality rules "
             "(tools/obs_diff.py)",
    )


def add_obs_args(parser: argparse.ArgumentParser) -> None:
    """Observability knobs shared by both CLIs (videop2p_tpu/obs)."""
    parser.add_argument(
        "--telemetry", action="store_true",
        help="thread fixed-shape per-step telemetry (loss curves, "
             "inner-steps-taken, latent abs-max/NaN counts) through the "
             "fused device programs — zero extra dispatches; decoded "
             "host-side into the run ledger",
    )
    parser.add_argument(
        "--ledger", type=str, default=None,
        help="write a JSONL run ledger (phases, XLA compile events, "
             "telemetry summaries, memory snapshots, per-program XLA "
             "cost/memory analyses) to this path; default when --telemetry "
             "is set: <output dir>/run_ledger.jsonl. Render with "
             "tools/ledger_summary.py; diff runs with tools/obs_diff.py",
    )
    parser.add_argument(
        "--no_program_analysis", action="store_true",
        help="skip the automatic compiled-program introspection "
             "(cost/memory analysis + HLO fingerprint per instrumented "
             "program on each compile) — it re-lowers each program "
             "ahead-of-time, which is persistent-cache-cheap but not free",
    )
    parser.add_argument(
        "--device_telemetry", action="store_true",
        help="per-device observability on sharded runs (obs/comm.py): "
             "per-device latent abs-max/mean/NaN stats and a cross-replica "
             "divergence scalar riding the fused scans via a shard_map "
             "probe, per-device memory snapshots, and divergence ledger "
             "events gated by the zero-noise-floor COMM_RULES verdict — "
             "requires --mesh; implies a run ledger",
    )
    parser.add_argument(
        "--latency", action="store_true",
        help="per-dispatch execute-latency distributions (obs/timing.py): "
             "every instrumented program accumulates dispatch-return vs "
             "block-until-ready wall-clock into bounded reservoirs, "
             "flushed as execute_timing ledger events (p50/p95/p99/max + "
             "the dispatch-vs-blocked async-overlap split) and gated by "
             "TIMING_RULES; implies a run ledger. Trades async-dispatch "
             "overlap for measured end-to-end latency — values bit-exact "
             "either way",
    )
    parser.add_argument(
        "--trace_analysis", action="store_true",
        help="capture a jax.profiler device trace around the main phase "
             "and mine the raw *.xplane.pb with the stdlib reader "
             "(obs/trace.py — no tensorflow): per-op-family device time, "
             "top ops, compute/collective overlap fraction and idle gaps "
             "as a trace_analysis ledger event + .npz sidecar; implies a "
             "run ledger",
    )
    parser.add_argument(
        "--attn_maps", action="store_true",
        help="capture per-step cross-attention observability riding the "
             "fused DDIM scans (obs/attention.py): pooled per-token "
             "heatmaps, per-site attention entropies, the LocalBlend mask "
             "time series — arrays land in an .npz sidecar referenced by "
             "attn_maps ledger events; capture-off programs stay bit-exact",
    )
    parser.add_argument(
        "--quality", action="store_true",
        help="compute edit-quality metrics after decode (obs/quality.py): "
             "inversion-reconstruction PSNR/SSIM vs the input frames, "
             "background-preservation PSNR outside the blend mask, "
             "adjacent-frame consistency — emitted as a quality ledger "
             "event and gated by the quality RegressionRules",
    )
    parser.add_argument(
        "--report", action="store_true",
        help="render a self-contained HTML edit report (per-word heatmap "
             "grids, mask overlays, null-text loss sparkline, quality "
             "table, regression verdicts) next to the run's outputs — "
             "tools/edit_report.py re-renders it from the ledger+sidecar",
    )
    parser.add_argument(
        "--incidents", type=str, default=None, metavar="DIR",
        help="arm the incident plane (obs/incident.py): an always-on "
             "flight recorder tees the run ledger's most recent events "
             "into a bounded in-memory ring, and anomaly triggers (burn "
             "alert, circuit-breaker open, dispatch deadline, poisoned "
             "stream window, unhandled crash, SIGUSR1 on demand) write "
             "debounced atomic capture bundles under DIR — flight-ring "
             "JSONL, tsdb snapshot, /healthz+/metrics from every target, "
             "manifest with fingerprints and trace-id exemplars. Render "
             "a bundle with tools/incident_report.py",
    )


def dependent_suffix(
    *,
    dependent: bool,
    decay_rate: float,
    window_size: int,
    ar_sample: bool,
    ar_coeff: float,
    eta: float,
    dependent_weights: float,
) -> str:
    """The exact Stage-1↔Stage-2 path contract (run_tuning.py:97-99)."""
    return "_dependent{d}_dr{dr}_ws{ws}_ar{ar}_ac{ac}_eta{e}_dw{dw}".format(
        d=dependent, dr=decay_rate, ws=window_size, ar=ar_sample, ac=ar_coeff,
        e=eta, dw=dependent_weights,
    )


def _is_pipeline_dir(path: str) -> bool:
    return os.path.isdir(os.path.join(path, "unet")) or os.path.isfile(
        os.path.join(path, "model_index.json")
    )


def resolve_pipeline_dir(base_path: str, **suffix_kwargs) -> str:
    """Apply the Stage-1↔Stage-2 suffix contract, tolerating already-resolved
    dirs.

    The reference blindly appends the suffix (run_videop2p.py:74-78), which
    breaks when the caller (e.g. the demo UI's experiment picker) already
    holds the suffixed pipeline dir — the doubled path doesn't exist and
    model loading silently fell back to random init. Preference order:
    suffixed dir if it holds a pipeline, else the given dir if it does, else
    the suffixed dir (downstream loading warns about the missing checkpoint).
    """
    suffixed = base_path + dependent_suffix(**suffix_kwargs)
    if _is_pipeline_dir(suffixed):
        return suffixed
    if _is_pipeline_dir(base_path):
        if suffixed != base_path:
            print(f"[resolve_pipeline_dir] {base_path!r} is already a pipeline "
                  "dir — not appending the dependent suffix")
        return base_path
    return suffixed


@dataclass
class ModelBundle:
    unet: Any
    unet_params: Dict
    vae: Any
    vae_params: Optional[Dict]
    text_encoder: Any
    text_params: Optional[Dict]
    tokenizer: Any
    random_init: bool
    source_dir: Optional[str]
    # the checkpoint's scheduler_config.json (empty for random init) — Stage-2
    # builds its DDIM scheduler from this (run_videop2p.py:101-114)
    scheduler_config: Optional[Dict] = None
    # cached jitted text-encoder apply (a fresh jax.jit wrapper per call would
    # retrace every encode_prompts invocation)
    _text_apply: Any = None

    def make_scheduler(self):
        from videop2p_tpu.core import DDIMScheduler

        if self.scheduler_config:
            return DDIMScheduler.from_config(self.scheduler_config)
        return DDIMScheduler.create_sd()


def build_models(
    pretrained_model_path: Optional[str],
    *,
    dtype: jnp.dtype = jnp.bfloat16,
    frame_attention: str = "auto",
    gradient_checkpointing: bool = False,
    tiny: bool = False,
    seed: int = 0,
) -> ModelBundle:
    """Load a diffusers-layout checkpoint dir, or build random-init models.

    Random init (no checkpoint on disk) keeps every code path drivable in
    weightless environments — outputs are noise, wall-clock is real.
    """
    from videop2p_tpu.models import (
        AutoencoderKL,
        CLIPTextConfig,
        CLIPTextEncoder,
        UNet3DConditionModel,
        UNet3DConfig,
        VAEConfig,
    )
    from videop2p_tpu.utils.tokenizers import load_tokenizer

    key = jax.random.key(seed)
    has_ckpt = pretrained_model_path is not None and os.path.isdir(
        os.path.join(pretrained_model_path, "unet")
    )
    if has_ckpt:
        from videop2p_tpu.models.pipeline_io import load_pipeline

        loaded = load_pipeline(
            pretrained_model_path,
            dtype=dtype,
            frame_attention=frame_attention,
            gradient_checkpointing=gradient_checkpointing,
        )
        if loaded.inflation_report["kept_init"]:
            print(
                f"[build_models] inflated 2D checkpoint: "
                f"{len(loaded.inflation_report['kept_init'])} temporal params keep init"
            )
        tokenizer = load_tokenizer(pretrained_model_path)
        vae, vae_params = loaded.vae, loaded.vae_params
        text_encoder, text_params = loaded.text_encoder, loaded.text_params
        if vae is None or text_encoder is None:
            # a Stage-1 run that started weightless saves only the UNet — the
            # frozen components have no tuned weights to persist. Backfill
            # with random init so the smoke path stays drivable end-to-end.
            warnings.warn(
                f"checkpoint {pretrained_model_path!r} has no "
                f"{'vae' if vae is None else ''}"
                f"{'/' if vae is None and text_encoder is None else ''}"
                f"{'text_encoder' if text_encoder is None else ''} — "
                "backfilling with RANDOM-INIT components",
                stacklevel=2,
            )
            ucfg = loaded.unet.config
            small = ucfg.block_out_channels[0] < 64  # tiny-shaped checkpoint
            key = jax.random.key(seed)
            if vae is None:
                vcfg = VAEConfig.tiny() if small else VAEConfig()
                vae = AutoencoderKL(config=vcfg, dtype=dtype)
                vae_params = dict(jax.jit(vae.init)(
                    key, jnp.zeros((1, 64, 64, vcfg.in_channels), dtype), key
                ))
            if text_encoder is None:
                ccfg = (
                    CLIPTextConfig.tiny(hidden_size=ucfg.cross_attention_dim)
                    if small else CLIPTextConfig()
                )
                text_encoder = CLIPTextEncoder(config=ccfg, dtype=dtype)
                text_params = dict(jax.jit(text_encoder.init)(
                    key, jnp.zeros((1, 8), jnp.int32)
                ))
        return ModelBundle(
            unet=loaded.unet,
            unet_params=loaded.unet_params,
            vae=vae,
            vae_params=vae_params,
            text_encoder=text_encoder,
            text_params=text_params,
            tokenizer=tokenizer,
            random_init=False,
            source_dir=pretrained_model_path,
            scheduler_config=loaded.scheduler_config,
        )

    warnings.warn(
        f"no checkpoint at {pretrained_model_path!r} — building RANDOM-INIT "
        "models (smoke/benchmark mode; outputs will be noise)",
        stacklevel=2,
    )
    ucfg = UNet3DConfig.tiny() if tiny else UNet3DConfig.sd15()
    ucfg = type(ucfg)(**{
        **ucfg.__dict__,
        "frame_attention": frame_attention,
        "gradient_checkpointing": gradient_checkpointing,
    })
    vcfg = VAEConfig.tiny() if tiny else VAEConfig()
    ccfg = CLIPTextConfig.tiny() if tiny else CLIPTextConfig()
    if tiny:
        ucfg = type(ucfg)(**{**ucfg.__dict__, "cross_attention_dim": ccfg.hidden_size})
    unet = UNet3DConditionModel(config=ucfg, dtype=dtype)
    vae = AutoencoderKL(config=vcfg, dtype=dtype)
    text_encoder = CLIPTextEncoder(config=ccfg, dtype=dtype)
    s = ucfg.sample_size
    probe = jnp.zeros((1, 2, s, s, ucfg.in_channels), dtype)
    tprobe = jnp.zeros((1, 77, ucfg.cross_attention_dim), dtype)
    unet_params = jax.jit(unet.init)(key, probe, jnp.asarray(0), tprobe)
    vae_params = jax.jit(vae.init)(key, jnp.zeros((1, 64, 64, vcfg.in_channels), dtype), key)
    text_params = jax.jit(text_encoder.init)(key, jnp.zeros((1, 8), jnp.int32))
    return ModelBundle(
        unet=unet,
        unet_params=dict(unet_params),
        vae=vae,
        vae_params=dict(vae_params),
        text_encoder=text_encoder,
        text_params=dict(text_params),
        tokenizer=load_tokenizer(None),
        random_init=True,
        source_dir=None,
    )


def encode_prompts(bundle: ModelBundle, prompts) -> jax.Array:
    """(P, 77, D) text embeddings via the bundled CLIP encoder."""
    ids = jnp.asarray(
        [bundle.tokenizer.encode_padded(p) for p in prompts], jnp.int32
    )
    if bundle._text_apply is None:
        bundle._text_apply = jax.jit(bundle.text_encoder.apply)
    return bundle._text_apply(bundle.text_params, ids)
