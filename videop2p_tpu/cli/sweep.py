"""Hyperparameter sweep driver for the dependent-noise study.

Re-design of the reference's per-scene sweep scripts (/root/reference/run_car.py,
run_rabbit.py): a grid over ``decay_rate x eta x dependent_weights`` where each
cell runs the (tune, p2p) config pair as subprocesses — the stages already
communicate through the dependent-suffix path contract, so the sweep only has
to pass identical flags to both. Instead of one hardcoded script per scene,
the scene is a parameter.

Run:  python -m videop2p_tpu.cli.sweep --scene rabbit-jump \
          --decay_rates 0.1 0.3 --etas 0.0 0.1 --dependent_weights 0.0 0.2
"""

from __future__ import annotations

import argparse
import itertools
import subprocess
import sys
from typing import List, Optional


def cell_commands(
    tune_config: str,
    p2p_config: str,
    *,
    decay_rate: float,
    eta: float,
    dependent_weight: float,
    window_size: int,
    ar_sample: bool,
    ar_coeff: float,
    num_frames: int,
    fast: bool,
    dependent_p2p: bool,
    extra: List[str],
    inv_store: Optional[str] = None,
) -> List[List[str]]:
    """The two subprocess argvs for one grid cell (run_rabbit.py:36-56).

    ``inv_store`` routes every cell's Stage-2 inversion persistence through
    ONE shared content-addressed root (the ``serve/store.py`` disk layer):
    cells whose inversion determinants agree (same clip, checkpoint, steps,
    dependent settings) reuse one DDIM inversion instead of re-walking it
    per scenario; cells that differ miss by key construction — sharing is
    always safe."""
    common = [
        "--dependent",
        "--decay_rate", str(decay_rate),
        "--eta", str(eta),
        "--dependent_weights", str(dependent_weight),
        "--window_size", str(window_size),
        "--ar_coeff", str(ar_coeff),
        "--num_frames", str(num_frames),
    ]
    if ar_sample:
        common.append("--ar_sample")
    tune = [sys.executable, "-m", "videop2p_tpu.cli.run_tuning",
            "--config", tune_config] + common + extra
    p2p = [sys.executable, "-m", "videop2p_tpu.cli.run_videop2p",
           "--config", p2p_config] + common + extra
    if inv_store:
        p2p += ["--inv_store", inv_store]
    if fast:
        p2p.append("--fast")
    if dependent_p2p:
        p2p.append("--dependent_p2p")
    return [tune, p2p]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--scene", type=str, default="rabbit-jump",
                    help="config pair stem: configs/<scene>-{tune,p2p}.yaml")
    ap.add_argument("--tune_config", type=str, default=None)
    ap.add_argument("--p2p_config", type=str, default=None)
    ap.add_argument("--decay_rates", type=float, nargs="+", default=[0.1])
    ap.add_argument("--etas", type=float, nargs="+", default=[0.0])
    ap.add_argument("--dependent_weights", type=float, nargs="+", default=[0.0])
    ap.add_argument("--window_size", type=int, default=8)
    ap.add_argument("--ar_sample", action="store_true")
    ap.add_argument("--ar_coeff", type=float, default=0.1)
    ap.add_argument("--num_frames", type=int, default=8)
    ap.add_argument("--fast", action="store_true")
    ap.add_argument("--dependent_p2p", action="store_true")
    ap.add_argument("--skip_tune", action="store_true",
                    help="reuse existing Stage-1 checkpoints, only re-edit")
    ap.add_argument("--inv_store", type=str, default="inv_store",
                    help="shared inversion-store root every cell's Stage-2 "
                         "run persists/reuses DDIM inversions through "
                         "(serve/store.py disk layer; content-addressed "
                         "keys make sharing always safe)")
    ap.add_argument("--no_inv_store", action="store_true",
                    help="per-cell inversion persistence only (the "
                         "pre-store layout under each results dir)")
    ap.add_argument("--dry_run", action="store_true", help="print commands only")
    # everything the sweep doesn't recognize is forwarded to both stages in
    # original order (flag-style extras like `--tiny` or `--width 256` work
    # without a `--` separator; a positional catch-all would split a flag
    # from its value)
    args, unknown = ap.parse_known_args(argv)
    args.extra = unknown

    tune_config = args.tune_config or f"configs/{args.scene}-tune.yaml"
    p2p_config = args.p2p_config or f"configs/{args.scene}-p2p.yaml"
    grid = list(itertools.product(args.decay_rates, args.etas, args.dependent_weights))
    print(f"[sweep] {len(grid)} cells over {args.scene}")
    failures = 0
    for decay_rate, eta, dw in grid:
        cmds = cell_commands(
            tune_config, p2p_config,
            decay_rate=decay_rate, eta=eta, dependent_weight=dw,
            window_size=args.window_size, ar_sample=args.ar_sample,
            ar_coeff=args.ar_coeff, num_frames=args.num_frames,
            fast=args.fast, dependent_p2p=args.dependent_p2p,
            extra=list(args.extra),
            inv_store=None if args.no_inv_store else args.inv_store,
        )
        if args.skip_tune:
            cmds = cmds[1:]
        for cmd in cmds:
            print("[sweep]", " ".join(cmd))
            if args.dry_run:
                continue
            ret = subprocess.call(cmd)
            if ret != 0:
                print(f"[sweep] FAILED (exit {ret}): dr={decay_rate} eta={eta} dw={dw}")
                failures += 1
                break  # don't run p2p on a failed tune
    print(f"[sweep] done, {failures} failed cell(s)")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
