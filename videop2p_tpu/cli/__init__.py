"""Command-line entry points (Stage-1 tuning, Stage-2 editing, sweeps)."""
