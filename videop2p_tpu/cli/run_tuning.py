"""Stage-1 one-shot tuning entry point.

TPU-native re-design of /root/reference/run_tuning.py: same YAML schema
(configs/rabbit-jump-tune.yaml) and flag surface, driving the pure
``train_step`` in a host loop with checkpointing, resume, and the
inversion+sampling validation the reference runs every ``validation_steps``
(run_tuning.py:346-375). Ends by writing the diffusers-layout pipeline dir
Stage 2 consumes (run_tuning.py:387-393).

Run:  python -m videop2p_tpu.cli.run_tuning --config configs/rabbit-jump-tune.yaml
"""

from __future__ import annotations

import argparse
import contextlib
import json
import os
import signal
import threading
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from videop2p_tpu.cli.common import (
    add_dependent_args,
    add_obs_args,
    build_models,
    dependent_suffix,
    encode_prompts,
    load_config,
    make_run_ledger,
    setup_mesh,
    enable_compile_cache,
)
from videop2p_tpu.obs import instrumented_jit
from videop2p_tpu.core import DDIMScheduler, DDPMScheduler, DependentNoiseSampler
from videop2p_tpu.data import SingleVideoDataset
from videop2p_tpu.models import decode_video, encode_video
from videop2p_tpu.models.pipeline_io import save_pipeline
from videop2p_tpu.pipelines import ddim_inversion, edit_sample, make_unet_fn
from videop2p_tpu.train import (
    TrainState,
    TuneConfig,
    latest_checkpoint,
    make_lr_schedule,
    make_optimizer,
    restore_checkpoint,
    save_checkpoint,
    train_steps,
)
from videop2p_tpu.utils.metrics import MetricsLogger
from videop2p_tpu.utils.profiling import phase_timer
from videop2p_tpu.utils.video_io import save_videos_grid

# preemption safety (ISSUE 9 satellite): SIGTERM/SIGINT set this event; the
# training loop checks it at every chunk boundary, saves a final checkpoint
# through the existing train/checkpoint.py machinery and exits cleanly.
# Auto-resume (`resume_from_checkpoint: latest`) then continues
# BIT-IDENTICALLY: per-step noise keys derive from (run key, absolute step)
# inside train_steps, so the resume boundary cannot change the noise
# sequence — tests/test_train.py pins interrupted+resumed == uninterrupted.
_PREEMPT_EVENT = threading.Event()


def _preempt_handler(signum, frame):
    _PREEMPT_EVENT.set()


def _install_preempt_handlers():
    """Install SIGTERM/SIGINT → checkpoint-then-exit; returns a restore
    callable. No-op off the main thread (the signal API restriction) —
    embedded callers keep their own handlers."""
    if threading.current_thread() is not threading.main_thread():
        return lambda: None
    prev = {}
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            prev[sig] = signal.signal(sig, _preempt_handler)
        except (ValueError, OSError):  # exotic embeddings
            continue
    def _restore():
        for sig, h in prev.items():
            try:
                signal.signal(sig, h)
            except (ValueError, OSError):
                continue
    return _restore


def main(
    pretrained_model_path: str,
    output_dir: str,
    train_data: Dict[str, Any],
    validation_data: Dict[str, Any],
    learning_rate: float = 3e-5,
    train_batch_size: int = 1,
    max_train_steps: int = 500,
    checkpointing_steps: int = 1000,
    validation_steps: int = 500,
    trainable_modules=("attn1.to_q", "attn2.to_q", "attn_temp"),
    seed: Optional[int] = None,
    mixed_precision: str = "fp16",
    gradient_checkpointing: bool = True,
    gradient_accumulation_steps: int = 1,
    max_grad_norm: float = 1.0,
    lr_scheduler: str = "constant",
    lr_warmup_steps: int = 0,
    scale_lr: bool = False,
    resume_from_checkpoint: Optional[str] = None,
    prediction_type: str = "epsilon",
    # fork flags (run_tuning.py:401-412)
    dependent: bool = False,
    num_frames: int = 60,
    decay_rate: float = 0.1,
    window_size: int = 60,
    ar_sample: bool = False,
    ar_coeff: float = 0.1,
    eta: float = 0.0,
    dependent_weights: float = 0.0,
    # device mesh "dp,sp,tp" — shards the tuning step across chips: frames
    # over sp (ring attention at uncontrolled temporal sites), attention/FF
    # kernels over tp. Single-clip tuning needs dp=1.
    mesh: Optional[str] = None,
    # extras (not in the reference)
    tiny: bool = False,
    log_every: int = 50,
    # train steps per device call (lax.scan chunk): amortizes the per-call
    # dispatch overhead (~1.3 s through the TPU tunnel — recorded per-step
    # rate is device-floor + 1300/K ms, so K=25 read 437 ms vs the 388 ms
    # device floor and K=100 amortizes to ~400 ms; a 100-step call is ~40 s,
    # inside the execution watchdog that kills multi-minute programs)
    steps_per_call: int = 100,
    # observability (videop2p_tpu/obs): per-step loss + grad-norm telemetry
    # riding the train scan + a JSONL run ledger
    telemetry: bool = False,
    ledger: Optional[str] = None,
    # distributed observability (ISSUE 5, obs/comm.py): after training,
    # measure the cross-replica divergence of the tuned params over the
    # mesh axes they are replicated on — the invariant a desynced replica
    # breaks silently — and ledger it (divergence must be 0.0; COMM_RULES)
    device_telemetry: bool = False,
    # time-domain observability (ISSUE 6, obs/timing.py + obs/trace.py):
    # --latency accumulates per-dispatch (dispatch-return, blocked)
    # latencies of the train_steps program into bounded reservoirs →
    # execute_timing ledger events gated by TIMING_RULES;
    # --trace_analysis wraps the training loop in a jax.profiler capture
    # mined into a trace_analysis event by the stdlib xplane reader
    latency: bool = False,
    trace_analysis: bool = False,
    # --incidents DIR arms the incident plane (obs/incident.py): flight-
    # ring tee on the run ledger + crash/SIGUSR1 capture bundles
    incidents: Optional[str] = None,
    # automatic XLA cost/memory analysis of each instrumented program on
    # compile (program_analysis ledger events; obs/introspect.py)
    program_analysis: bool = True,
    **unused,
) -> str:
    del unused
    enable_compile_cache()
    n_frames = int(train_data.get("n_sample_frames", 8))
    output_dir = output_dir + dependent_suffix(
        dependent=dependent, decay_rate=decay_rate, window_size=window_size,
        ar_sample=ar_sample, ar_coeff=ar_coeff, eta=eta,
        dependent_weights=dependent_weights,
    )
    os.makedirs(output_dir, exist_ok=True)
    with open(os.path.join(output_dir, "config.json"), "w") as f:
        json.dump({k: v for k, v in locals().items()
                   if isinstance(v, (str, int, float, bool, dict, list, tuple, type(None)))},
                  f, indent=2, default=str)

    # unified run record (videop2p_tpu/obs): phases, compile events, train
    # metrics and telemetry land in one JSONL stream, line-flushed. The
    # flags→ledger wiring is shared with run_videop2p and the serving
    # engine (cli/common.make_run_ledger).
    run_ledger = make_run_ledger(
        os.path.join(output_dir, "run_ledger.jsonl"),
        ledger=ledger, mesh=mesh,
        meta={"cli": "run_tuning", "max_train_steps": max_train_steps},
        telemetry=telemetry, device_telemetry=device_telemetry,
        latency=latency, trace_analysis=trace_analysis,
        program_analysis=program_analysis, incidents=incidents,
    )

    sampler = None
    if dependent:
        if num_frames != n_frames:
            print(f"[tune] dependent sampler uses the clip's {n_frames} frames "
                  f"(--num_frames {num_frames} would not match the data)")
        sampler = DependentNoiseSampler.create(
            num_frames=n_frames, decay_rate=decay_rate,
            window_size=min(window_size, n_frames), ar_sample=ar_sample,
            ar_coeff=ar_coeff,
        )

    dtype = {"fp16": jnp.bfloat16, "bf16": jnp.bfloat16, "no": jnp.float32}[mixed_precision]
    bundle = build_models(
        pretrained_model_path, dtype=dtype, frame_attention="chunked",
        gradient_checkpointing=gradient_checkpointing, tiny=tiny,
        seed=seed or 0,
    )

    # data → latents (VAE encode once; the clip is fixed, run_tuning.py:282-287)
    ds = SingleVideoDataset(
        video_path=train_data["video_path"],
        prompt=train_data["prompt"],
        width=int(train_data.get("width", 512)),
        height=int(train_data.get("height", 512)),
        n_sample_frames=n_frames,
        sample_start_idx=int(train_data.get("sample_start_idx", 0)),
        sample_frame_rate=int(train_data.get("sample_frame_rate", 1)),
    )
    video = jnp.asarray(ds.load())[None]  # (1, F, H, W, 3)
    key = jax.random.key(seed if seed is not None else 0)
    key, ek = jax.random.split(key)
    with phase_timer("vae_encode"):
        latents = encode_video(bundle.vae, bundle.vae_params, video.astype(dtype), ek)
        latents = jax.block_until_ready(latents.astype(jnp.float32))
    text_emb = encode_prompts(bundle, [train_data["prompt"]])

    tune_cfg = TuneConfig(
        learning_rate=learning_rate,
        scale_lr=scale_lr,
        lr_scheduler=lr_scheduler,
        lr_warmup_steps=lr_warmup_steps,
        max_train_steps=max_train_steps,
        max_grad_norm=max_grad_norm,
        gradient_accumulation_steps=gradient_accumulation_steps,
        trainable_modules=tuple(trainable_modules),
        train_batch_size=train_batch_size,
    )
    tx = make_optimizer(tune_cfg)
    if mesh:
        from videop2p_tpu.parallel import latent_sharding

        # shard the bundle BEFORE TrainState.create so the partitioned
        # trainable/frozen trees (and the optimizer state initialized from
        # them) inherit the placements
        device_mesh = setup_mesh(bundle, mesh, n_frames)
        latents = jax.device_put(latents, latent_sharding(device_mesh))
    params = bundle.unet_params["params"]
    state = TrainState.create(params, tx, tune_cfg.trainable_modules)

    first_step = 0
    if resume_from_checkpoint:
        path = (
            latest_checkpoint(output_dir)
            if resume_from_checkpoint == "latest"
            else resume_from_checkpoint
        )
        if path:
            state = restore_checkpoint(path, state)
            first_step = int(state.step)
            print(f"[tune] resumed from {path} at step {first_step}")

    noise_sched = DDPMScheduler.create_sd(prediction_type=prediction_type)
    unet_fn = make_unet_fn(bundle.unet)
    # multiple steps per device call (lax.scan over the per-step keys): each
    # host dispatch rides the TPU tunnel, and the device-side step is ~2×
    # faster than the per-dispatch loop measured (train/tuner.py train_steps)
    # the state (params + Adam moments) is donated: the carry tree would
    # otherwise be held twice (in + out) inside the program and copied —
    # nothing else reads bundle.unet_params after TrainState.create above
    steps_fn = instrumented_jit(
        lambda s, k, n: train_steps(
            unet_fn, tx, s, noise_sched, latents, text_emb, k, num_steps=n,
            dependent_sampler=sampler, telemetry=telemetry,
        ),
        program="train_steps",
        static_argnums=2,
        donate_argnums=(0,),
    )

    # per-step train_loss/lr tracker (the reference's accelerator.log /
    # TensorBoard trackers, run_tuning.py:234,337,377-378); with an active
    # ledger every logged step also becomes a ledger `metric` event
    lr_schedule = make_lr_schedule(tune_cfg)
    metrics = MetricsLogger(output_dir, ledger=run_ledger)
    losses = []
    grad_norms = []  # telemetry mode only: per-step pre-clip global norm

    def flush_losses(next_step):
        # one sync for the whole buffer (per-step float() would serialize
        # host dispatch against device compute)
        flat = np.asarray(jax.block_until_ready(jnp.concatenate(losses)))
        gflat = (np.asarray(jax.block_until_ready(jnp.concatenate(grad_norms)))
                 if grad_norms else None)
        start = next_step - len(flat)
        for j, lv in enumerate(flat):
            rec = {"train_loss": float(lv), "lr": float(lr_schedule(start + j))}
            if gflat is not None:
                rec["grad_norm"] = float(gflat[j])
            metrics.log(start + j + 1, rec)
        losses.clear()
        grad_norms.clear()
        return float(flat[-1])

    # chunks align with the periodic boundaries so per-step losses,
    # checkpoints and validation keep their exact cadence; a cadence of
    # 0/None disables that feature entirely
    import math

    steps_per_call = max(int(steps_per_call), 1)
    cadences = [p for p in (log_every, checkpointing_steps, validation_steps)
                if p and p > 0]
    # distinct chunk lengths each compile their own scan program
    # (static_argnums) — round steps_per_call down to divide the cadences'
    # gcd when that keeps a useful chunk, so the loop reuses ONE executable
    g = math.gcd(*cadences) if cadences else steps_per_call
    if g > 1 and steps_per_call % g and g % steps_per_call:
        aligned = math.gcd(steps_per_call, g)
        if aligned >= 5:
            print(
                f"[tune] steps_per_call {steps_per_call} → {aligned} to align "
                f"with the log/checkpoint/validation cadences (gcd {g}); "
                "smaller chunks amortize the per-call dispatch overhead less "
                "— align the cadences to a multiple of steps_per_call to "
                "keep the full chunk"
            )
            steps_per_call = aligned
    t0 = time.perf_counter()
    # per-step noise keys derive from (this run key, absolute step) inside
    # train_steps — logging/checkpoint cadence and resume points cannot
    # change the training noise sequence
    key, train_key = jax.random.split(key)
    i = first_step
    traced_chunk = False
    preempted = False
    restore_signals = _install_preempt_handlers()
    while i < max_train_steps:
        nxt = min(
            [max_train_steps, i + steps_per_call]
            + [(i // p + 1) * p for p in cadences]
        )
        # --trace_analysis: capture ONE post-compile chunk (the second —
        # the first is dominated by the scan compile) and mine it into a
        # trace_analysis ledger event; tracing every chunk would write
        # gigabytes of xplane protos for a long tune
        do_trace = trace_analysis and not traced_chunk and i > first_step
        if do_trace:
            from videop2p_tpu.obs.trace import trace_window

            chunk_ctx = trace_window("train_steps_chunk")
        else:
            chunk_ctx = contextlib.nullcontext()
        with chunk_ctx:
            out = steps_fn(state, train_key, nxt - i)
            if do_trace:
                jax.block_until_ready(out)  # the capture must hold the work
                traced_chunk = True
        if telemetry:
            state, chunk_losses, chunk_gnorms = out
            grad_norms.append(chunk_gnorms)
        else:
            state, chunk_losses = out
        losses.append(chunk_losses)  # device-side; no per-chunk host sync
        first_chunk = i == first_step
        i = nxt
        if _PREEMPT_EVENT.is_set():
            # SIGTERM/SIGINT landed: save the final checkpoint at this
            # chunk boundary and exit cleanly (skip validation/export —
            # the resumed run redoes them); handled after the loop
            preempted = True
            break
        if (log_every and i % log_every == 0) or i == max_train_steps or first_chunk:
            loss = flush_losses(i)
            rate = (i - first_step) / max(time.perf_counter() - t0, 1e-9)
            print(f"[tune] step {i}/{max_train_steps} loss={loss:.4f} "
                  f"({rate:.2f} it/s)")
        if checkpointing_steps and i % checkpointing_steps == 0:
            save_checkpoint(output_dir, jax.device_get(state), i)
        if (validation_steps and i % validation_steps == 0) or i == max_train_steps:
            _validate(
                bundle, state, latents, validation_data, output_dir, i,
                dependent_weights=dependent_weights, sampler=sampler,
                text_emb=text_emb, key=key,
            )
    restore_signals()
    if preempted:
        if losses:
            flush_losses(i)
        metrics.close()
        ckpt_path = save_checkpoint(output_dir, jax.device_get(state), i)
        print(f"[tune] preempted at step {i} — checkpoint saved to "
              f"{ckpt_path}; resume with resume_from_checkpoint: latest")
        if run_ledger is not None:
            run_ledger.event("preempted", step=i, checkpoint=ckpt_path)
            run_ledger.close()
        return output_dir
    if losses:  # flush the tail of the buffer
        flush_losses(max_train_steps)
    metrics.close()
    if run_ledger is not None:
        run_ledger.memory_snapshot(note="after_training")
    if device_telemetry and mesh:
        # the tuned params must be IDENTICAL on every mesh replica (dp=1
        # single-clip tuning replicates non-tensor-parallel params over the
        # whole mesh); a nonzero divergence means a replica desynced — the
        # ledger event joins the zero-noise-floor COMM_RULES gate
        from videop2p_tpu.obs.comm import tree_replica_divergence

        div_axes = tuple(
            a for a in device_mesh.axis_names if device_mesh.shape[a] > 1
        )
        if div_axes:
            div = float(tree_replica_divergence(
                state.params, device_mesh, axes=div_axes
            ))
            if run_ledger is not None:
                run_ledger.divergence("params_after_training", div,
                                      axes=list(div_axes))
            print(f"[tune] param replica divergence over {div_axes}: {div}"
                  + ("  <-- REPLICAS DIVERGED (must be 0.0)" if div else ""))

    save_pipeline(
        output_dir,
        bundle.unet.config,
        {"params": state.params},
        source_dir=bundle.source_dir,
        scheduler_config={
            "_class_name": "DDIMScheduler",
            "beta_start": 0.00085,
            "beta_end": 0.012,
            "beta_schedule": "scaled_linear",
            "clip_sample": False,
            "set_alpha_to_one": False,
            "steps_offset": 1,
        },
    )
    print(f"[tune] saved pipeline to {output_dir}")
    if run_ledger is not None:
        run_ledger.event("artifacts", pipeline_dir=output_dir)
        run_ledger.close()
        print(f"[tune] run ledger: {run_ledger.path}")
    return output_dir


def run_distillation(
    pipeline_dir: str,
    train_data: Dict[str, Any],
    *,
    distill_steps: int,
    distill_grid: int = 50,
    distill_lr: float = 1e-4,
    distill_ema: float = 0.95,
    distill_boundary_weight: float = 1.0,
    tiny: bool = False,
    seed: Optional[int] = None,
    steps_per_call: int = 50,
) -> str:
    """Consistency-distill the few-step student from a tuned pipeline dir
    (ISSUE 16 — train/distill.py): the tuned UNet is the frozen teacher,
    the student re-trains the tuner's parameter subset plus the
    time-conditioning head against the self-consistency objective on the
    SAME clip latents the tuning used. Writes the servable student
    artifact to ``<pipeline_dir>/student/checkpoint-<step>`` — the path
    ``cli.serve --student_ckpt`` and ``ProgramSpec.student_ckpt`` take.
    Returns the checkpoint path."""
    from videop2p_tpu.train import (
        DistillConfig,
        DistillState,
        init_time_head,
        make_distill_optimizer,
        save_student,
    )
    from videop2p_tpu.train import distill_steps as distill_scan

    n_frames = int(train_data.get("n_sample_frames", 8))
    bundle = build_models(
        pipeline_dir, dtype=jnp.float32, frame_attention="chunked",
        tiny=tiny, seed=seed or 0,
    )
    ds = SingleVideoDataset(
        video_path=train_data["video_path"],
        prompt=train_data["prompt"],
        width=int(train_data.get("width", 512)),
        height=int(train_data.get("height", 512)),
        n_sample_frames=n_frames,
        sample_start_idx=int(train_data.get("sample_start_idx", 0)),
        sample_frame_rate=int(train_data.get("sample_frame_rate", 1)),
    )
    video = jnp.asarray(ds.load())[None]
    key = jax.random.key(seed if seed is not None else 0)
    key, ek, hk = jax.random.split(key, 3)
    with phase_timer("vae_encode"):
        latents = encode_video(
            bundle.vae, bundle.vae_params, video.astype(jnp.float32), ek
        )
        latents = jax.block_until_ready(latents.astype(jnp.float32))
    text_emb = encode_prompts(bundle, [train_data["prompt"]])

    cfg = DistillConfig(
        learning_rate=distill_lr,
        max_train_steps=distill_steps,
        distill_grid=distill_grid,
        ema_decay=distill_ema,
        boundary_weight=distill_boundary_weight,
    )
    tx = make_distill_optimizer(cfg)
    head = init_time_head(hk, bundle.unet.config)
    state = DistillState.create(
        bundle.unet_params["params"], head, tx, cfg.trainable_modules
    )
    sched = bundle.make_scheduler()  # the DDIM grid the student walks
    unet_fn = make_unet_fn(bundle.unet)
    steps_fn = instrumented_jit(
        lambda s, k, n: distill_scan(
            unet_fn, tx, s, sched, latents, text_emb, k,
            num_steps=n, cfg=cfg,
        ),
        program="distill_steps",
        static_argnums=2,
        donate_argnums=(0,),
    )
    key, dk = jax.random.split(key)
    steps_per_call = max(int(steps_per_call), 1)
    i, t0 = 0, time.perf_counter()
    while i < distill_steps:
        n = min(steps_per_call, distill_steps - i)
        state, chunk_losses = steps_fn(state, dk, n)
        i += n
        loss = float(np.asarray(jax.block_until_ready(chunk_losses))[-1])
        rate = i / max(time.perf_counter() - t0, 1e-9)
        print(f"[distill] step {i}/{distill_steps} loss={loss:.5f} "
              f"({rate:.2f} it/s)")
    path = save_student(
        os.path.join(pipeline_dir, "student"), jax.device_get(state), i
    )
    print(f"[distill] saved student to {path}")
    return path


def _validate(
    bundle, state, latents, validation_data, output_dir, step, *,
    dependent_weights, sampler, text_emb, key,
):
    """Inversion + sampling validation (run_tuning.py:346-375): DDIM-invert
    the training latents, store them, sample each validation prompt from the
    inverted noise, write a GIF grid."""
    num_inv = int(validation_data.get("num_inv_steps", 50))
    num_steps = int(validation_data.get("num_inference_steps", 50))
    guidance = float(validation_data.get("guidance_scale", 12.5))
    use_inv = bool(validation_data.get("use_inv_latent", True))
    prompts: List[str] = list(validation_data.get("prompts", []))
    unet_fn = make_unet_fn(bundle.unet)
    sched = DDIMScheduler.create_sd()
    params = {"params": state.params}

    with phase_timer("validation"):
        if use_inv:
            traj = ddim_inversion(
                unet_fn, params, sched, latents, text_emb,
                num_inference_steps=num_inv,
                dependent_weight=dependent_weights,
                dependent_sampler=sampler if dependent_weights > 0 else None,
                key=key,
            )
            x_t = traj[-1]
            inv_dir = os.path.join(output_dir, "inv_latents")
            os.makedirs(inv_dir, exist_ok=True)
            np.save(os.path.join(inv_dir, f"ddim_latent-{step}.npy"),
                    np.asarray(jax.device_get(x_t)))
        else:
            x_t = jax.random.normal(key, latents.shape, latents.dtype)

        # one compile shared by every validation prompt (same shapes)
        sample_fn = jax.jit(
            lambda p, xt, c, u: edit_sample(
                unet_fn, p, sched, xt, c, u,
                num_inference_steps=num_steps, guidance_scale=guidance,
            )
        )
        uncond = encode_prompts(bundle, [""])[0]
        videos = []
        for prompt in prompts:
            cond = encode_prompts(bundle, [prompt])
            out = sample_fn(params, x_t, cond, uncond)
            frames = decode_video(bundle.vae, bundle.vae_params, out.astype(jnp.float32))
            videos.append(np.asarray(jax.device_get((frames + 1) / 2))[0])
    if videos:
        path = os.path.join(output_dir, "samples", f"sample-{step}.gif")
        save_videos_grid(np.stack(videos), path)
        print(f"[tune] validation saved {path}")


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--config", type=str, required=True)
    parser.add_argument("--tiny", action="store_true",
                        help="random-init tiny models (weightless smoke mode)")
    parser.add_argument("--mesh", type=str, default=None,
                        help="device mesh 1,sp,tp (frames/tensor sharding)")
    # consistency distillation of the few-step student (ISSUE 16 —
    # train/distill.py; runs AFTER tuning, teacher = the tuned pipeline)
    parser.add_argument("--distill_steps", type=int, default=0,
                        help="consistency-distillation steps to run after "
                             "tuning (0 = off); writes the servable student "
                             "to <output_dir>/student/checkpoint-<N>")
    parser.add_argument("--distill_grid", type=int, default=50,
                        help="DDIM grid points the self-consistency chain "
                             "walks (the teacher's solver discretization)")
    parser.add_argument("--distill_lr", type=float, default=1e-4,
                        help="student learning rate (AdamW via the tuner's "
                             "partitioned optimizer)")
    parser.add_argument("--distill_ema", type=float, default=0.95,
                        help="EMA decay of the consistency target network")
    parser.add_argument("--distill_boundary_weight", type=float, default=1.0,
                        help="loss weight of the boundary term (final grid "
                             "point, target = the data x0)")
    add_dependent_args(parser)
    add_obs_args(parser)
    args = parser.parse_args()
    # multi-host: join the process group before any device use (no-op on a
    # single host; see parallel/distributed.py)
    from videop2p_tpu.parallel import initialize_distributed

    initialize_distributed()
    if args.attn_maps or args.quality or args.report:
        # the flags live in the shared add_obs_args surface; the semantic
        # layer instruments the EDIT pipelines (run_videop2p)
        print("[tune] --attn_maps/--quality/--report are Stage-2 (editing) "
              "knobs — ignored by the tuning CLI")
    cfg = load_config(args.config)
    args.mesh = args.mesh or cfg.pop("mesh", None)
    out_dir = main(
        **cfg,
        mesh=args.mesh,
        dependent=args.dependent,
        num_frames=args.num_frames,
        decay_rate=args.decay_rate,
        window_size=args.window_size,
        ar_sample=args.ar_sample,
        ar_coeff=args.ar_coeff,
        eta=args.eta,
        dependent_weights=args.dependent_weights,
        tiny=args.tiny,
        telemetry=args.telemetry,
        ledger=args.ledger,
        program_analysis=not args.no_program_analysis,
        device_telemetry=args.device_telemetry,
        latency=args.latency,
        trace_analysis=args.trace_analysis,
        incidents=args.incidents,
    )
    if args.distill_steps > 0:
        run_distillation(
            out_dir, cfg["train_data"],
            distill_steps=args.distill_steps,
            distill_grid=args.distill_grid,
            distill_lr=args.distill_lr,
            distill_ema=args.distill_ema,
            distill_boundary_weight=args.distill_boundary_weight,
            tiny=args.tiny,
            seed=cfg.get("seed"),
        )
