"""Edit-serving entry point: a persistent engine behind a JSON HTTP API.

Holds warm compiled programs (one :class:`~videop2p_tpu.serve.programs.
ProgramSet` per checkpoint/geometry/steps spec), a device-resident
inversion store, and a micro-batcher — so repeat and concurrent edits stop
paying per-invocation compiles and per-edit inversions (ROADMAP item 1).
See ``docs/SERVING.md`` for the architecture and the knob table.

Run:  python -m videop2p_tpu.cli.serve --checkpoint <pipeline-dir> --port 8000
      python -m videop2p_tpu.cli.serve --tiny --steps 4 --video_len 2   # smoke
"""

from __future__ import annotations

import argparse

from videop2p_tpu.cli.common import enable_compile_cache


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--checkpoint", type=str, default=None,
                    help="tuned pipeline dir (random-init smoke when absent)")
    ap.add_argument("--width", type=int, default=512)
    ap.add_argument("--video_len", type=int, default=8)
    ap.add_argument("--steps", type=int, default=50)
    ap.add_argument("--guidance_scale", type=float, default=7.5)
    ap.add_argument("--tiny", action="store_true",
                    help="random-init tiny models (weightless smoke mode)")
    ap.add_argument("--mixed_precision", type=str, default="fp32",
                    choices=["fp32", "no", "fp16", "bf16"])
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--mesh", type=str, default=None,
                    help="dp,sp,tp — sp/tp shard the model; dp>1 is the "
                         "serving data axis batched dispatches shard over")
    ap.add_argument("--ring_variant", type=str, default="overlap",
                    choices=["overlap", "bidir", "serial"],
                    help="ring-attention rotation schedule on sp>1 meshes "
                         "(parallel/ring.py): overlap = double-buffered "
                         "n-1 rotations, bidir = split halves on both ICI "
                         "directions; enters the spec fingerprint")
    ap.add_argument("--tp_collectives", type=str, default="gspmd",
                    choices=["gspmd", "psum_scatter"],
                    help="row-parallel output reduction on tp>1 meshes: "
                         "declarative all-reduce vs the explicit Megatron "
                         "reduce-scatter seam; enters the spec fingerprint")
    ap.add_argument("--host", type=str, default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8000)
    ap.add_argument("--out_dir", type=str, default="serve_out",
                    help="per-request artifact dir (GIFs, the serve ledger)")
    ap.add_argument("--store_budget_gb", type=float, default=4.0,
                    help="device-resident inversion-store byte budget (LRU)")
    ap.add_argument("--inv_store", type=str, default=None,
                    help="disk write-through root for inversion trajectories "
                         "(shared with the CLIs' --inv_store)")
    ap.add_argument("--max_batch", type=int, default=4,
                    help="micro-batch cap per dispatch")
    ap.add_argument("--max_wait_ms", type=float, default=50.0,
                    help="admit-window deadline before dispatching a partial "
                         "batch")
    ap.add_argument("--batch_dispatch", type=str, default="scan",
                    choices=["scan", "vmap"],
                    help="scan: one dispatch, per-request math bit-exact vs "
                         "singleton; vmap: vectorized + data-mesh sharded")
    # scheduling policy + per-tenant QoS (ISSUE 11 — serve/sched.py,
    # docs/SERVING.md "Fleet")
    ap.add_argument("--scheduler", type=str, default="drain",
                    choices=["drain", "continuous", "fair"],
                    help="batching policy: drain = classic plan-boundary "
                         "windows (pre-scheduler behavior, bit-exact); "
                         "continuous = iteration-level admission (new "
                         "compatible requests join the NEXT dispatch, "
                         "deadline-aware ordering); fair = per-tenant "
                         "priority lanes + deficit-round-robin QoS")
    ap.add_argument("--tenants", type=str, default=None,
                    help="per-tenant QoS config: 'name:weight[:priority]' "
                         "pairs (e.g. 'A:5,B:1') or a JSON object with "
                         "weight/priority/deadline_s per tenant; requests "
                         "pick their lane via the 'tenant' field")
    ap.add_argument("--max_batch_wait_ms", type=float, default=None,
                    help="cap any request's total batch-formation wait "
                         "(drain: bounds the admit window by the first "
                         "request's time-in-queue; continuous: the partial-"
                         "batch fill hold). Default: unbounded (bit-exact "
                         "drain baseline)")
    ap.add_argument("--batch_order", type=str, default="first_seen",
                    choices=["first_seen", "oldest"],
                    help="drain-policy dispatch order of planned chunks: "
                         "first_seen (pre-scheduler behavior) or oldest "
                         "(by each chunk's oldest member — an early rare-"
                         "key singleton no longer delays the dominant "
                         "key's batch)")
    ap.add_argument("--ledger", type=str, default=None,
                    help="serve ledger path (default <out_dir>/serve_ledger"
                         ".jsonl) — live /metrics reads its reservoirs")
    ap.add_argument("--no_warm", action="store_true",
                    help="skip the startup compile warm-up")
    ap.add_argument("--warm_prompts", type=str, nargs=2,
                    default=["a video", "an edited video"],
                    help="source/edit prompt pair whose controller structure "
                         "the warm-up compiles for")
    ap.add_argument("--step_buckets", type=int, nargs="*", default=[],
                    help="additional few-step edit variants to warm (e.g. "
                         "20 8): exact timestep subsets of --steps served "
                         "from the SAME inversion products; per-request "
                         "'steps' outside the warmed buckets is a 400")
    # per-UNet-call cost levers (ISSUE 15 — models/quant.py,
    # pipelines/reuse.py; docs/PERF_ANALYSIS.md "Per-call cost")
    ap.add_argument("--quant_mode", type=str, default="off",
                    choices=["off", "w8", "w8a8"],
                    help="UNet weight quantization at set build: w8 = int8 "
                         "weights with per-output-channel scales (1-byte "
                         "program inputs, dequantized at the matmul seam); "
                         "w8a8 adds dynamic activation fake-quant at the "
                         "attention Dense boundaries. Fixed per set — "
                         "requests asserting another mode get a 400; "
                         "enters the spec fingerprint")
    ap.add_argument("--reuse_schedule", type=str, default="off",
                    help="default cross-step deep-feature reuse schedule "
                         "('uniform:K' or 'custom:<p0,p1,...>'): designated "
                         "steps run the full UNet, the rest reuse the "
                         "cached deep feature through a shallow path — "
                         "still ONE compiled program; enters the spec "
                         "fingerprint")
    ap.add_argument("--reuse_buckets", type=str, nargs="*", default=[],
                    help="additional reuse schedules to warm; per-request "
                         "'reuse_schedule' outside the warmed set is a 400")
    # consistency-distilled few-step student (ISSUE 16 — train/distill.py;
    # docs/PERF_ANALYSIS.md "Few-step student")
    ap.add_argument("--student_ckpt", type=str, default=None,
                    help="consistency-distilled student checkpoint "
                         "(train/distill.py save_student): the distilled "
                         "trainable subset + time-conditioning head serve "
                         "requests with 'student': true over the SAME "
                         "teacher inversion products; enters the spec "
                         "fingerprint")
    ap.add_argument("--student_buckets", type=int, nargs="*", default=[],
                    help="student step buckets to warm (e.g. 1 2 4); a "
                         "request with 'student': true outside the warmed "
                         "buckets — or without --student_ckpt — is a 400 "
                         "listing the warmed options")
    # resilience knobs (ISSUE 9 — docs/SERVING.md "Failure semantics")
    ap.add_argument("--max_queue", type=int, default=64,
                    help="bounded admit queue: over this many in-flight "
                         "requests, submits shed with HTTP 429")
    ap.add_argument("--deadline_s", type=float, default=None,
                    help="default per-request deadline (seconds from "
                         "submit); expired requests fail with terminal "
                         "status deadline_exceeded")
    ap.add_argument("--dispatch_timeout_s", type=float, default=None,
                    help="watchdog budget around each device dispatch: past "
                         "it the batch fails deadline_exceeded instead of "
                         "wedging the engine")
    ap.add_argument("--max_retries", type=int, default=2,
                    help="transient dispatch failures retry this many times "
                         "(capped jitter-free exponential backoff)")
    ap.add_argument("--breaker_threshold", type=int, default=3,
                    help="consecutive dispatch failures that trip the "
                         "circuit breaker open (submits then fast-fail 503 "
                         "with Retry-After)")
    ap.add_argument("--breaker_open_s", type=float, default=5.0,
                    help="open-window seconds before the breaker half-opens "
                         "for its recovery probe")
    ap.add_argument("--drain_s", type=float, default=5.0,
                    help="graceful-shutdown window: SIGTERM/SIGINT stops "
                         "admitting and gives queued work this long before "
                         "failing leftovers with engine_closed")
    ap.add_argument("--faults", type=str, default=None,
                    help="deterministic fault-injection plan (serve/faults"
                         ".py DSL, e.g. 'fail@2,hang@4:1.5,unavail@5-7,"
                         "corrupt:*'); also via VIDEOP2P_SERVE_FAULTS — "
                         "chaos testing only")
    # request tracing + SLOs (ISSUE 14 — docs/OBSERVABILITY.md Layer 5)
    ap.add_argument("--tracing", action="store_true",
                    help="request-scoped distributed tracing (obs/spans"
                         ".py): every request's admit→queue→resolve→"
                         "dispatch→decode lifecycle lands as span ledger "
                         "events; inbound traceparent headers continue the "
                         "caller's trace — join ledgers with "
                         "tools/trace_view.py. Off: bit-exact, zero "
                         "per-request overhead")
    ap.add_argument("--slo", action="store_true",
                    help="evaluate the default SLO objectives (obs/slo.py: "
                         "availability, deadline-miss rate, served p99) "
                         "over the run at shutdown into slo_report ledger "
                         "events — obs_diff SLO_RULES gate budget burn")
    # incident plane (ISSUE 18 — docs/OBSERVABILITY.md Layer 7)
    ap.add_argument("--incidents", type=str, default=None, metavar="DIR",
                    help="arm the incident plane (obs/incident.py): the "
                         "flight recorder tees ledger events into a "
                         "bounded ring, and breaker-open / dispatch-"
                         "deadline / crash / SIGUSR1 triggers write "
                         "debounced atomic capture bundles under DIR — "
                         "render with tools/incident_report.py")
    return ap


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    enable_compile_cache()
    from videop2p_tpu.parallel import initialize_distributed

    initialize_distributed()
    from videop2p_tpu.serve import EditEngine, FaultPlan, ProgramSpec
    from videop2p_tpu.serve.http import make_server

    spec = ProgramSpec(
        checkpoint=args.checkpoint, width=args.width,
        video_len=args.video_len, steps=args.steps,
        guidance_scale=args.guidance_scale, tiny=args.tiny,
        mixed_precision=args.mixed_precision, seed=args.seed, mesh=args.mesh,
        ring_variant=args.ring_variant, tp_collectives=args.tp_collectives,
        quant_mode=args.quant_mode, reuse_schedule=args.reuse_schedule,
        student_ckpt=args.student_ckpt,
    )
    faults = FaultPlan.parse(args.faults) if args.faults else None
    if faults is not None:
        print(f"[serve] CHAOS MODE: injecting fault plan {args.faults!r}")
    engine = EditEngine(
        spec,
        out_dir=args.out_dir,
        store_budget_bytes=int(args.store_budget_gb * (1 << 30)),
        persist_dir=args.inv_store,
        max_batch=args.max_batch,
        max_wait_s=args.max_wait_ms / 1000.0,
        batch_dispatch=args.batch_dispatch,
        scheduler=args.scheduler,
        tenants=args.tenants,
        max_batch_wait_s=(args.max_batch_wait_ms / 1000.0
                          if args.max_batch_wait_ms is not None else None),
        batch_order=args.batch_order,
        ledger_path=args.ledger,
        max_queue=args.max_queue,
        default_deadline_s=args.deadline_s,
        dispatch_timeout_s=args.dispatch_timeout_s,
        max_retries=args.max_retries,
        breaker_threshold=args.breaker_threshold,
        breaker_open_s=args.breaker_open_s,
        faults=faults,
        tracing=args.tracing,
        slo=args.slo,
        incidents=args.incidents,
    )
    if not args.no_warm:
        print(f"[serve] warming programs (spec {engine.spec.fingerprint()})...")
        info = engine.warm(tuple(args.warm_prompts),
                           batch_sizes=(min(2, args.max_batch),),
                           step_buckets=tuple(args.step_buckets),
                           reuse_schedules=tuple(args.reuse_buckets),
                           student_steps=tuple(args.student_buckets))
        print(f"[serve] warm in {info['seconds']}s "
              f"(batch sizes {info['batch_sizes']}, "
              f"step buckets {info['steps']}, "
              f"reuse {info['reuse']}, quant {info['quant']}, "
              f"student {info['student']})")
    server = make_server(engine, host=args.host, port=args.port)
    print(f"[serve] listening on {server.url}  "
          f"(ledger: {engine.ledger.path})")

    # graceful drain-then-exit on SIGTERM (the orchestrator's preemption
    # signal): stop the HTTP loop from a helper thread — calling shutdown()
    # inside the handler would deadlock, the handler runs ON the thread
    # serve_forever is blocking — then the finally below drains the engine
    # (in-flight work gets --drain_s to finish; leftovers fail with the
    # terminal engine_closed status instead of hanging clients forever)
    import signal
    import threading

    def _sigterm(signum, frame):
        print("[serve] SIGTERM — draining")
        threading.Thread(target=server.httpd.shutdown, daemon=True).start()

    try:
        signal.signal(signal.SIGTERM, _sigterm)
    except ValueError:  # not the main thread (embedded use) — skip
        pass
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        print("[serve] shutting down")
    finally:
        server.httpd.server_close()
        engine.close(drain_s=args.drain_s)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
