"""Fleet entry point: a stdlib HTTP router over N edit-engine replicas.

Two ways to get a fleet (docs/SERVING.md "Fleet"):

  * route over ALREADY-RUNNING engines (their own ``cli/serve.py``
    processes, possibly on other hosts) —

      python -m videop2p_tpu.cli.router \
          --replicas http://host-a:8000,http://host-b:8000 --port 9000

  * spawn local subprocess replicas first (one ``cli/serve.py`` child per
    replica on its own port, all sharing ``--inv_store``), then route —

      python -m videop2p_tpu.cli.router --spawn 2 --tiny --steps 4 \
          --video_len 2 --inv_store shared/inv --port 9000

The router load-balances on each replica's ``/healthz`` status and
``/metrics`` queue/latency gauges, routes around open circuit breakers,
retries transient submit failures deterministically, and serves the
aggregated fleet ``/healthz`` + ``/metrics``. Clients are unchanged — the
router speaks the same JSON API as a single engine.
"""

from __future__ import annotations

import argparse


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--replicas", type=str, default=None,
                    help="comma-separated base URLs of running engines "
                         "(mutually exclusive with --spawn)")
    ap.add_argument("--spawn", type=int, default=None,
                    help="spawn this many local cli/serve.py subprocess "
                         "replicas sharing --inv_store before routing")
    # spec knobs forwarded to spawned replicas
    ap.add_argument("--checkpoint", type=str, default=None)
    ap.add_argument("--width", type=int, default=512)
    ap.add_argument("--video_len", type=int, default=8)
    ap.add_argument("--steps", type=int, default=50)
    ap.add_argument("--tiny", action="store_true")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out_dir", type=str, default="router_out",
                    help="router ledger + spawned-replica artifact root")
    ap.add_argument("--inv_store", type=str, default=None,
                    help="shared content-addressed disk inversion-store "
                         "root (default <out_dir>/inv_store) — what makes "
                         "replicas a fleet: an inversion on one is a disk "
                         "store-hit on every other")
    ap.add_argument("--serve_arg", action="append", default=[],
                    help="extra flag forwarded verbatim to every spawned "
                         "replica (repeatable), e.g. --serve_arg=--scheduler"
                         " --serve_arg=continuous")
    # router knobs
    ap.add_argument("--host", type=str, default="127.0.0.1")
    ap.add_argument("--port", type=int, default=9000)
    ap.add_argument("--ledger", type=str, default=None,
                    help="router ledger path (default <out_dir>/"
                         "router_ledger.jsonl) — router_health lands here")
    ap.add_argument("--timeout_s", type=float, default=30.0,
                    help="per-replica request timeout")
    ap.add_argument("--max_retries", type=int, default=2,
                    help="full routing passes retried (deterministic "
                         "backoff) before the router answers 503")
    ap.add_argument("--suspend_s", type=float, default=1.0,
                    help="suspect window after a replica refuses a submit")
    ap.add_argument("--probe_ttl_s", type=float, default=0.5,
                    help="health/metrics probe cache TTL")
    ap.add_argument("--tracing", action="store_true",
                    help="request-scoped tracing (obs/spans.py): record a "
                         "router.submit span per routed request and "
                         "forward a child traceparent to the chosen "
                         "replica — run the replicas with --tracing too "
                         "and join the ledgers with tools/trace_view.py")
    ap.add_argument("--incidents", type=str, default=None, metavar="DIR",
                    help="arm the incident plane (obs/incident.py): the "
                         "router ledger tees into a flight ring, replicas "
                         "become bundle probe targets, and crash/SIGUSR1 "
                         "triggers write debounced capture bundles under "
                         "DIR — render with tools/incident_report.py")
    return ap


def main(argv=None) -> int:
    import os
    import signal
    import threading

    args = build_parser().parse_args(argv)
    if bool(args.replicas) == bool(args.spawn):
        build_parser().error("exactly one of --replicas / --spawn required")

    supervisor = None
    if args.spawn:
        from videop2p_tpu.serve.programs import ProgramSpec
        from videop2p_tpu.serve.replica import ReplicaSupervisor

        spec = ProgramSpec(checkpoint=args.checkpoint, width=args.width,
                           video_len=args.video_len, steps=args.steps,
                           tiny=args.tiny, seed=args.seed)
        supervisor = ReplicaSupervisor(
            spec, args.spawn, mode="subprocess", out_dir=args.out_dir,
            persist_dir=args.inv_store, host=args.host,
            serve_argv=list(args.serve_arg),
        )
        print(f"[router] spawning {args.spawn} replicas "
              f"(shared store: {supervisor.persist_dir})...")
        supervisor.start()
        urls = supervisor.urls
    else:
        urls = [u.strip() for u in args.replicas.split(",") if u.strip()]

    from videop2p_tpu.serve.router import Router, RouterServer

    os.makedirs(args.out_dir, exist_ok=True)
    router = Router(
        urls,
        timeout_s=args.timeout_s, max_retries=args.max_retries,
        suspend_s=args.suspend_s, probe_ttl_s=args.probe_ttl_s,
        ledger_path=(args.ledger
                     or os.path.join(args.out_dir, "router_ledger.jsonl")),
        tracing=args.tracing,
        incidents=args.incidents,
    )
    server = RouterServer(router, host=args.host, port=args.port)
    print(f"[router] listening on {server.url} over {len(urls)} replica(s):")
    for u in urls:
        print(f"[router]   {u}")

    def _sigterm(signum, frame):
        print("[router] SIGTERM — shutting down")
        threading.Thread(target=server.httpd.shutdown, daemon=True).start()

    try:
        signal.signal(signal.SIGTERM, _sigterm)
    except ValueError:  # not the main thread (embedded use)
        pass
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        print("[router] shutting down")
    finally:
        server.httpd.server_close()
        router.close()
        if supervisor is not None:
            supervisor.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
