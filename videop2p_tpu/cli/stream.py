"""Streaming long-video editing: minutes of footage, not 64 frames.

Chunks a long clip into overlapping ``--video_len``-frame windows, runs
every window through a warm in-process serving engine (windows are just
requests — the scheduler batches compatible ones), crossfades the edited
windows back together, and persists a per-window job manifest under
``--job_dir`` so a killed / preempted / crashed job RESUMES from its last
completed window with bit-identical output (``docs/STREAMING.md``).

SIGTERM / SIGINT checkpoint-then-exit: the driver stops submitting new
windows, harvests what is in flight (so those windows persist), writes
the ``stream_health`` summary with ``interrupted=1`` and exits cleanly —
rerun the same command to continue.

Run:  python -m videop2p_tpu.cli.stream --checkpoint <dir> \\
          --image data/long_clip --prompt "a rabbit is jumping" \\
          --edit_prompt "a origami rabbit is jumping" --job_dir job1
      python -m videop2p_tpu.cli.stream --tiny --synthetic 20 \\
          --video_len 4 --steps 2 --overlap 1 --job_dir /tmp/job  # smoke
"""

from __future__ import annotations

import argparse
import json

from videop2p_tpu.cli.common import enable_compile_cache


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(description=__doc__)
    # clip source
    ap.add_argument("--image", type=str, default=None,
                    help="frame directory of the LONG clip (every frame is "
                         "loaded; windows slice it)")
    ap.add_argument("--synthetic", type=int, default=None, metavar="F",
                    help="generate a deterministic F-frame synthetic clip "
                         "instead of --image (CPU smoke / chaos drills)")
    ap.add_argument("--prompt", type=str, default="a rabbit is jumping")
    ap.add_argument("--edit_prompt", type=str,
                    default="a origami rabbit is jumping")
    ap.add_argument("--job_dir", type=str, required=True,
                    help="the job's persistent state: manifest.json, "
                         "per-window sidecars, the final video, the engine "
                         "artifacts and the run ledger. Rerunning with the "
                         "same dir RESUMES the job")
    ap.add_argument("--no_resume", action="store_true",
                    help="ignore a persisted manifest and recompute every "
                         "window (the disk inversion store still amortizes)")
    # window geometry
    ap.add_argument("--overlap", type=int, default=2,
                    help="frames shared (and crossfaded) between adjacent "
                         "windows; the window size itself is --video_len")
    ap.add_argument("--window_retries", type=int, default=2,
                    help="per-window job-level retries before the window is "
                         "declared poisoned and degrades to passthrough")
    ap.add_argument("--max_inflight", type=int, default=4,
                    help="windows submitted concurrently (lets the engine "
                         "scheduler batch compatible windows; memory per "
                         "window stays flat — results are harvested and "
                         "released as they land)")
    ap.add_argument("--no_degrade", action="store_true",
                    help="a poisoned window kills the job instead of "
                         "degrading to a recorded passthrough")
    # edit parameters (the per-window request surface)
    ap.add_argument("--is_word_swap", action="store_true")
    ap.add_argument("--blend_word", type=str, nargs=2, default=None)
    ap.add_argument("--cross_replace_steps", type=float, default=0.2)
    ap.add_argument("--self_replace_steps", type=float, default=0.5)
    ap.add_argument("--seed", type=int, default=0)
    # spec knobs (mirror cli/serve.py)
    ap.add_argument("--checkpoint", type=str, default=None)
    ap.add_argument("--width", type=int, default=512)
    ap.add_argument("--video_len", type=int, default=8,
                    help="frames per window — the warm programs' geometry")
    ap.add_argument("--steps", type=int, default=50)
    ap.add_argument("--guidance_scale", type=float, default=7.5)
    ap.add_argument("--tiny", action="store_true")
    ap.add_argument("--mixed_precision", type=str, default="fp32",
                    choices=["fp32", "no", "fp16", "bf16"])
    ap.add_argument("--mesh", type=str, default=None)
    ap.add_argument("--ring_variant", type=str, default="overlap",
                    choices=["overlap", "bidir", "serial"])
    ap.add_argument("--tp_collectives", type=str, default="gspmd",
                    choices=["gspmd", "psum_scatter"])
    # engine knobs
    ap.add_argument("--store_budget_gb", type=float, default=4.0)
    ap.add_argument("--max_batch", type=int, default=4)
    ap.add_argument("--scheduler", type=str, default="continuous",
                    choices=["drain", "continuous", "fair"],
                    help="batching policy for the window requests "
                         "(continuous keeps devices full as windows land)")
    ap.add_argument("--max_retries", type=int, default=2,
                    help="engine-level transient dispatch retries under "
                         "each window")
    ap.add_argument("--dispatch_timeout_s", type=float, default=None)
    ap.add_argument("--ledger", type=str, default=None,
                    help="run-ledger path (default <job_dir>/stream_ledger"
                         ".jsonl) — stream_window / stream_seam / "
                         "stream_health events land here")
    ap.add_argument("--faults", type=str, default=None,
                    help="deterministic chaos plan (serve/faults.py DSL; "
                         "fail@K / hang@K:S hit window dispatches, "
                         "corrupt:manifest tears manifest writes) — "
                         "chaos testing only")
    ap.add_argument("--tracing", action="store_true",
                    help="request-scoped tracing (obs/spans.py): the job "
                         "gets a root stream.job span with one "
                         "stream.window child per window (resumed windows "
                         "show as cached spans) plus the engine's full "
                         "per-request span tree — render with "
                         "tools/trace_view.py")
    ap.add_argument("--incidents", type=str, default=None, metavar="DIR",
                    help="arm the incident plane (obs/incident.py): the "
                         "job ledger tees into a flight ring, and "
                         "breaker-open / deadline / poisoned-window / "
                         "crash triggers write debounced capture bundles "
                         "under DIR (default off) — render with "
                         "tools/incident_report.py")
    return ap


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if (args.image is None) == (args.synthetic is None):
        build_parser().error("pass exactly one of --image / --synthetic")
    enable_compile_cache()
    import os
    import signal
    import threading

    import numpy as np

    from videop2p_tpu.serve import EditEngine, FaultPlan, ProgramSpec
    from videop2p_tpu.stream import run_stream_job, synthetic_clip

    spec = ProgramSpec(
        checkpoint=args.checkpoint, width=args.width,
        video_len=args.video_len, steps=args.steps,
        guidance_scale=args.guidance_scale, tiny=args.tiny,
        mixed_precision=args.mixed_precision, seed=args.seed, mesh=args.mesh,
        ring_variant=args.ring_variant, tp_collectives=args.tp_collectives,
    )
    resolved = spec.resolved()
    if args.synthetic is not None:
        frames = synthetic_clip(args.synthetic, resolved.width,
                                seed=args.seed)
    else:
        from videop2p_tpu.data import load_frame_sequence

        frames = load_frame_sequence(args.image, size=resolved.width)
    faults = FaultPlan.parse(args.faults) if args.faults else None
    if faults is not None:
        print(f"[stream] CHAOS MODE: injecting fault plan {args.faults!r}")
    os.makedirs(args.job_dir, exist_ok=True)
    engine = EditEngine(
        spec,
        out_dir=os.path.join(args.job_dir, "serve_out"),
        store_budget_bytes=int(args.store_budget_gb * (1 << 30)),
        persist_dir=os.path.join(args.job_dir, "inv_store"),
        max_batch=args.max_batch,
        scheduler=args.scheduler,
        max_retries=args.max_retries,
        dispatch_timeout_s=args.dispatch_timeout_s,
        ledger_path=(args.ledger
                     or os.path.join(args.job_dir, "stream_ledger.jsonl")),
        keep_videos=True,
        faults=faults,
        tracing=args.tracing,
        incidents=args.incidents,
    )
    prompts = [args.prompt, args.edit_prompt]
    print(f"[stream] warming programs (spec {engine.spec.fingerprint()})...")
    engine.warm(tuple(prompts), batch_sizes=(min(2, args.max_batch),))

    # checkpoint-then-exit on SIGTERM/SIGINT (the orchestrator's preemption
    # signal — same contract as run_tuning): the driver checks the event
    # between windows, persists everything already harvested, and returns;
    # rerunning the same command resumes from the manifest
    stop_event = threading.Event()

    def _handler(signum, frame):
        print(f"[stream] signal {signum} — checkpointing then exiting")
        stop_event.set()

    installed = []
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            installed.append((sig, signal.signal(sig, _handler)))
        except ValueError:  # not the main thread (embedded use)
            pass
    try:
        result = run_stream_job(
            engine, frames, prompts,
            job_dir=args.job_dir,
            overlap=args.overlap,
            seed=args.seed,
            request_kwargs=dict(
                is_word_swap=args.is_word_swap,
                blend_word=args.blend_word,
                cross_replace_steps=args.cross_replace_steps,
                self_replace_steps=args.self_replace_steps,
            ),
            window_retries=args.window_retries,
            max_inflight=args.max_inflight,
            resume=not args.no_resume,
            degrade=not args.no_degrade,
            stop_event=stop_event,
            faults=faults,
        )
    finally:
        for sig, old in installed:
            signal.signal(sig, old)
        engine.close()
    print(json.dumps({"stream_health": result.health}, default=str))
    if result.complete:
        print(f"[stream] done: {result.health['windows_done']} edited + "
              f"{result.health['windows_passthrough']} passthrough window(s) "
              f"-> {os.path.join(args.job_dir, 'final.npy')}")
        assert result.video is not None and np.isfinite(result.video).all()
        return 0
    print("[stream] interrupted — rerun the same command to resume "
          f"({result.health['windows_done'] + result.health['windows_skipped']}"
          f"/{result.health['windows_total']} windows persisted)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
