"""Stage-2 attention-controlled editing entry point.

TPU-native re-design of /root/reference/run_videop2p.py: same YAML schema
(configs/rabbit-jump-p2p.yaml) and flag surface. Flow (run_videop2p.py:42-701):
load the Stage-1 pipeline dir (with the fork's dependent-suffix path
contract), load + VAE-encode the frame sequence, DDIM-invert it, optionally
run null-text optimization (full mode), build the controller from the edit
spec, run the controlled CFG denoise, and write two GIFs — the inversion
reconstruction stream and the edited stream (run_videop2p.py:692-701).

Run:  python -m videop2p_tpu.cli.run_videop2p --config configs/rabbit-jump-p2p.yaml --fast
"""

from __future__ import annotations

import argparse
import contextlib
import os
import time
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from videop2p_tpu.cli.common import (
    add_dependent_args,
    add_null_text_args,
    add_obs_args,
    load_config,
    make_run_ledger,
    resolve_pipeline_dir,
    enable_compile_cache,
)
from videop2p_tpu.core import DependentNoiseSampler
from videop2p_tpu.obs import instrumented_jit, program_label
from videop2p_tpu.data import load_frame_sequence
from videop2p_tpu.models import decode_video
from videop2p_tpu.pipelines import (
    ddim_inversion,
    edit_sample,
    make_unet_fn,
    null_text_optimization,
    null_text_optimization_fused,
)
from videop2p_tpu.utils.profiling import phase_timer
from videop2p_tpu.utils.video_io import save_video_gif

# module-level working-point constants (run_videop2p.py:32-40)
NUM_DDIM_STEPS = 50
GUIDANCE_SCALE = 7.5
MASK_TH = (0.3, 0.3)


def _word_token_records(prompts: Sequence[str], tokenizer) -> list:
    """Word → token-position records for every prompt (the report's key
    for slicing per-word heatmaps out of the per-token capture)."""
    from videop2p_tpu.control.schedules import get_word_inds

    recs, seen = [], set()
    for pi, text in enumerate(prompts):
        for word in text.split():
            if (pi, word) in seen:
                continue
            seen.add((pi, word))
            toks = get_word_inds(text, word, tokenizer)
            if len(toks):
                recs.append({"prompt": pi, "word": word,
                             "tokens": [int(t) for t in toks]})
    return recs


def _ledger_device_stats(run_ledger, program, dev_stats, probe) -> None:
    """Summarize one scan's device-probe channels into a
    ``device_telemetry`` ledger event (+ a console warning when the
    replicas diverged — divergence joins the zero-noise-floor COMM_RULES
    gate via obs/history.py)."""
    from videop2p_tpu.obs import summarize_device_stats

    rec = summarize_device_stats(dev_stats, probe.device_ids)
    rec["divergence_axes"] = list(probe.divergence_axes)
    if run_ledger is not None:
        run_ledger.device_telemetry(program, rec)
    div = rec.get("divergence_max", 0.0)
    line = (f"[p2p] device telemetry ({program}): {rec.get('devices')} "
            f"devices, divergence_max={div}")
    if div:
        line += "  <-- REPLICAS DIVERGED (must be 0.0)"
    print(line)


def _semantic_obs(
    run_ledger,
    *,
    output_folder: str,
    save_name: str,
    suffix: str,
    prompts: Sequence[str],
    tokenizer,
    attn_records: Dict,
    stream_map: Dict,
    quality: bool,
    report: bool,
    source01: np.ndarray,
    videos: np.ndarray,
) -> Optional[str]:
    """Post-decode semantic observability: the ``.npz`` sidecar, the
    ``attn_maps``/``quality`` ledger events, cross-run regression verdicts
    (quality rules included), and the self-contained HTML report. Returns
    the report path when one was written."""
    from videop2p_tpu.obs.attention import save_obs_sidecar, summarize_attn_record

    sidecar_path = os.path.join(
        output_folder, f"obs_sidecar_{save_name}{suffix}.npz"
    )
    sidecar: Dict[str, np.ndarray] = {}
    word_recs = _word_token_records(prompts, tokenizer)
    summaries = {}
    for scope, rec in attn_records.items():
        sidecar[f"attn_{scope}/cross_heat"] = np.asarray(rec["cross_heat"])
        for site, curve in sorted(rec.get("entropy", {}).items()):
            sidecar[f"attn_{scope}/entropy/{site}"] = np.asarray(curve)
        for k in ("mask_cov", "mask_heat", "blend_active"):
            if k in rec:
                sidecar[f"attn_{scope}/{k}"] = np.asarray(rec[k])
        summaries[scope] = summarize_attn_record(rec)

    # reference frames for the report's overlays, bounded at 128px
    stride = max(1, int(videos.shape[-3]) // 128)
    to_u8 = lambda v: (np.clip(v[:, ::stride, ::stride], 0, 1) * 255).astype(np.uint8)  # noqa: E731
    sidecar["frames/source"] = to_u8(np.asarray(source01))
    sidecar["frames/recon"] = to_u8(videos[0])
    sidecar["frames/edit"] = to_u8(videos[1])

    quality_summary = None
    if quality:
        from videop2p_tpu.obs.quality import edit_quality_record

        mask = None
        mh = attn_records.get("edit", {}).get("mask_heat")
        if mh is not None:
            mh = np.asarray(mh)  # (T, P, F, rh, rw), source stream first
            if mh.ndim == 5 and mh.shape[1] >= 2:
                m = np.clip(mh[-1, 1], 0.0, 1.0)  # final step, first edit
                F, H, W = videos.shape[1], videos.shape[2], videos.shape[3]
                yi = (np.arange(H) * m.shape[1] // max(H, 1)).clip(0, m.shape[1] - 1)
                xi = (np.arange(W) * m.shape[2] // max(W, 1)).clip(0, m.shape[2] - 1)
                mask = m[:F][:, yi][:, :, xi]
        quality_summary, curves = edit_quality_record(
            np.asarray(source01), videos[0], videos[1], mask=mask
        )
        for k, v in curves.items():
            sidecar[f"quality/{k}"] = v

    save_obs_sidecar(sidecar_path, sidecar)

    for scope, summary in summaries.items():
        streams = stream_map.get(scope, [])
        run_ledger.event(
            "attn_maps", scope=scope, program=f"attn_{scope}",
            sidecar=sidecar_path, streams=streams,
            words=[w for w in word_recs if w["prompt"] in streams],
            **summary,
        )
    if quality_summary is not None:
        run_ledger.event("quality", program="edit_quality",
                         sidecar=sidecar_path, **quality_summary)
        print("[p2p] quality: " + ", ".join(
            f"{k}={v}" for k, v in quality_summary.items()))

    # cross-run regression verdicts (PR-3 engine + the quality rules):
    # the ledger file appends across invocations, so a repeat run has its
    # baseline in the same file — best-effort, never takes the run down
    try:
        from videop2p_tpu.obs import history as _history
        from videop2p_tpu.obs.ledger import read_ledger

        recs = [_history.extract_run(r)
                for r in _history.split_runs(read_ledger(run_ledger.path))]
        if len(recs) >= 2:
            cur = recs[-1]
            base = _history.RunHistory(recs[:-1]).baseline_for(cur) or recs[-2]
            res = _history.evaluate_rules(base, cur)
            run_ledger.event("regression_verdicts",
                             baseline_run_id=base.get("run_id"), **res)
            if not res["pass"]:
                print(f"[p2p] REGRESSIONS vs run {base.get('run_id')}: "
                      + ", ".join(v["rule"] for v in res["regressions"]))
    except Exception as e:  # noqa: BLE001 — observability never kills a run
        print(f"[p2p] regression verdicts skipped: {e}")

    report_path = None
    if report:
        from videop2p_tpu.obs.report import write_report

        report_path = write_report(
            run_ledger.path,
            os.path.join(output_folder, f"report_{save_name}{suffix}.html"),
            sidecar_path,
        )
        print(f"[p2p] edit report: {report_path}")
    return report_path


def main(
    pretrained_model_path: str,
    image_path: str,
    prompt: str,
    prompts: Sequence[str],
    save_name: str,
    is_word_swap: bool,
    eq_params: Optional[Dict] = None,
    blend_word: Optional[Sequence[str]] = None,
    cross_replace_steps: float = 0.2,
    self_replace_steps: float = 0.5,
    video_len: int = 8,
    fast: bool = False,
    mixed_precision: str = "fp32",
    # fork flags (run_videop2p.py:708-720)
    dependent: bool = False,
    dependent_p2p: bool = False,
    num_frames: int = 60,
    decay_rate: float = 0.1,
    window_size: int = 60,
    ar_sample: bool = False,
    ar_coeff: float = 0.1,
    eta: float = 0.0,
    dependent_weights: float = 0.0,
    # per-frame text-embedding mode (pipeline_tuneavideo.py:341,366-367)
    multi: bool = False,
    # device mesh "dp,sp,tp" — shards the edit across chips: frames over sp
    # (sequence parallel, ring attention on uncontrolled temporal sites),
    # attention/FF kernels over tp. Single-video Stage-2 needs dp=1.
    mesh: Optional[str] = None,
    # extras (not in the reference)
    tiny: bool = False,
    width: int = 512,
    num_inner_steps: int = 10,
    # null-text inner-loop precision: "mixed" runs the optimization's UNet
    # forwards in bf16 (a bf16-compute clone of the UNet over the same
    # params) with fp32 scheduler/Adam/loss islands (pipelines/inversion.py)
    null_text_precision: str = "fp32",
    # how the per-step uncond embedding is produced (pipelines/inversion.py):
    # "optimize" = the reference's per-step inner Adam loop; "amortized" =
    # closed-form negative-prompt-inversion substitute (zero inner Adam
    # steps — the structural attack on the 91%-of-e2e null-text phase);
    # "hybrid" = amortized seed + K<=3 refinement steps batched jointly
    # across all outer steps. Parity gated by the quality rules.
    null_text_mode: str = "optimize",
    # 0 = the fused single-dispatch donated-trajectory program;
    # N>0 = N-step host-dispatched chunks (execution-watchdog fallback)
    null_text_chunk: int = 0,
    seed: int = 0,
    # cached-source fast mode (pipelines/cached.py): drop the source stream
    # from the edit batch and replay it exactly from the inversion trajectory;
    # applies in --fast with eta=0 (sharded meshes included — GSPMD shards
    # the capture trees over frames; tests/test_parallel.py pins
    # sharded==unsharded), else falls back live
    cached_source: bool = True,
    # per-UNet-call cost levers (ISSUE 15). quant_mode quantizes the UNet
    # weights at load (models/convert.quantize_unet_params — int8 storage,
    # per-output-channel scales, dequantized inside the traced program);
    # reuse_schedule ("uniform:K" / "custom:<p0,...>") enables cross-step
    # deep-feature reuse in the cached edit scan (pipelines/reuse.py) and
    # requires the cached fast path. Both "off" by default — the off paths
    # are pinned bit-exact.
    quant_mode: str = "off",
    reuse_schedule: str = "off",
    # persist/reuse inversion products under the results dir so a repeat edit
    # of the same clip skips DDIM inversion and null-text entirely (the
    # reference's commented-out intent, run_videop2p.py:663-673)
    reuse_inversion: bool = True,
    # shared content-addressed root for those persisted products
    # (serve/store.py disk layer): sweeps and repeat invocations across
    # DIFFERENT output dirs amortize one inversion per clip. Default (None)
    # keeps the per-results-dir layout.
    inv_store: Optional[str] = None,
    # observability (videop2p_tpu/obs): in-program telemetry riding the
    # fused scans + a JSONL run ledger (phases, compile events, memory)
    telemetry: bool = False,
    ledger: Optional[str] = None,
    # semantic observability (ISSUE 4): per-step cross-attention capture
    # riding the same fused scans (obs/attention.py), post-decode edit-
    # quality metrics (obs/quality.py), and the self-contained HTML run
    # report (obs/report.py / tools/edit_report.py). Any of them implies
    # a run ledger (default path) — the events are the report's input.
    attn_maps: bool = False,
    quality: bool = False,
    report: bool = False,
    # distributed observability (ISSUE 5, obs/comm.py): a shard_map probe
    # riding the fused edit scan records per-device latent stats and a
    # cross-replica divergence scalar (device_telemetry ledger events —
    # divergence must be 0.0, gated by the zero-noise-floor COMM_RULES);
    # requires --mesh. comm_analysis events (collective counts/bytes) come
    # free with program_analysis on sharded programs.
    device_telemetry: bool = False,
    # time-domain observability (ISSUE 6): --latency accumulates every
    # instrumented dispatch's (dispatch-return, block-until-ready)
    # latencies into bounded per-program reservoirs (obs/timing.py),
    # flushed as execute_timing ledger events and gated by TIMING_RULES;
    # --trace_analysis wraps the main edit program in a jax.profiler
    # capture mined by the stdlib xplane reader (obs/trace.py) into a
    # trace_analysis event (+ .npz sidecar) with the compute/collective
    # overlap fraction. Both imply a run ledger; both off paths are
    # bit-exact (host-side measurement only).
    latency: bool = False,
    trace_analysis: bool = False,
    # --incidents DIR arms the incident plane (obs/incident.py): flight-
    # ring tee on the run ledger + crash/SIGUSR1 capture bundles
    incidents: Optional[str] = None,
    # automatic XLA cost/memory analysis of each instrumented program on
    # compile (program_analysis ledger events; obs/introspect.py) — the
    # per-program peak-HBM estimate the memory snapshots are checked
    # against, and what tools/obs_diff.py regresses across runs
    program_analysis: bool = True,
    **unused,
) -> Tuple[str, str]:
    """Returns the (inversion_gif, edit_gif) paths it wrote."""
    del unused
    enable_compile_cache()
    if not program_analysis:
        os.environ["VIDEOP2P_OBS_NO_ANALYSIS"] = "1"
    if tiny and width == 512:
        # the tiny VAE downsamples 2×, not 8× — keep latents at the tiny
        # UNet's 8×8 working point so smoke runs stay small
        width = 16
    # Stage-1 ↔ Stage-2 path contract: the tuning run mangled its output dir
    # with the dependent hyperparameters (run_videop2p.py:74-78); results land
    # inside the checkpoint dir under results_dp{dependent_p2p} (:79).
    # Already-suffixed dirs (e.g. from the demo UI's picker) pass through.
    pretrained_model_path = resolve_pipeline_dir(
        pretrained_model_path,
        dependent=dependent, decay_rate=decay_rate, window_size=window_size,
        ar_sample=ar_sample, ar_coeff=ar_coeff, eta=eta,
        dependent_weights=dependent_weights,
    )
    output_folder = os.path.join(pretrained_model_path, f"results_dp{dependent_p2p}")
    suffix = "_fast" if fast else ""
    inversion_gif = os.path.join(output_folder, f"inversion{suffix}.gif")
    edit_gif = os.path.join(output_folder, f"{save_name}{suffix}.gif")
    os.makedirs(output_folder, exist_ok=True)

    # unified run record: every phase_timer region, XLA compile, decoded
    # telemetry summary and memory snapshot below lands in ONE JSONL stream
    # (events are line-flushed, so a killed run keeps what it measured).
    # The flags→ledger wiring is shared with run_tuning and the serving
    # engine (cli/common.make_run_ledger).
    run_ledger = make_run_ledger(
        os.path.join(output_folder, "run_ledger.jsonl"),
        ledger=ledger, mesh=mesh,
        meta={"cli": "run_videop2p", "fast": fast, "save_name": save_name,
              "prompt": prompt, "prompts": list(prompts),
              "null_text_precision": null_text_precision,
              "null_text_mode": null_text_mode},
        telemetry=telemetry, attn_maps=attn_maps, quality=quality,
        report=report, device_telemetry=device_telemetry, latency=latency,
        trace_analysis=trace_analysis, incidents=incidents,
    )

    def maybe_trace(window_name: str):
        """--trace_analysis: a mined jax.profiler capture around the
        named program region; a no-op context otherwise."""
        if trace_analysis:
            from videop2p_tpu.obs.trace import trace_window

            return trace_window(window_name)
        return contextlib.nullcontext()

    sampler = None
    if dependent_p2p or (dependent and eta > 0):
        sampler = DependentNoiseSampler.create(
            num_frames=video_len, decay_rate=decay_rate,
            window_size=min(window_size, video_len), ar_sample=ar_sample,
            ar_coeff=ar_coeff,
        )

    # model assembly, scheduler and the shared instrumented programs now
    # come from ONE ProgramSet (serve/programs.py) — the same object the
    # serving engine holds warm, so the program this CLI dispatches IS the
    # program the server batches. mixed_precision sets the model compute
    # dtype (the reference keeps the Stage-2 UNet fp32 — the fp32 default
    # here matches that); scheduler/latent math stays fp32 in every mode,
    # which is what carries inversion fidelity and the cached replay's
    # exactness. Full mode differentiates through the UNet (null-text
    # optimization); per-block remat keeps that backward inside one chip's
    # HBM (gradient_checkpointing=not fast).
    from videop2p_tpu.serve.programs import ProgramSet, ProgramSpec

    from videop2p_tpu.pipelines.reuse import validate_reuse_schedule

    reuse_schedule = validate_reuse_schedule(reuse_schedule, NUM_DDIM_STEPS)
    if reuse_schedule != "off" and not (cached_source and fast and eta == 0):
        raise ValueError(
            "reuse_schedule is a cached-fast-path knob: it needs --fast with "
            "eta=0 and cached_source (the deep-feature cache rides the fused "
            "edit scan)"
        )
    if quant_mode != "off" and not fast:
        raise ValueError(
            "quant_mode is an INFERENCE knob: full mode differentiates "
            "through the UNet (null-text optimization) and must see the "
            "full-precision weights — run it with --fast"
        )
    program_set = ProgramSet(ProgramSpec(
        checkpoint=pretrained_model_path, width=width, video_len=video_len,
        steps=NUM_DDIM_STEPS, guidance_scale=GUIDANCE_SCALE, tiny=tiny,
        mixed_precision=mixed_precision, seed=seed, mesh=mesh,
        gradient_checkpointing=not fast,
        quant_mode=quant_mode, reuse_schedule=reuse_schedule,
    ))
    bundle, dtype = program_set.bundle, program_set.dtype
    device_mesh = program_set.mesh

    # the per-device probe needs a mesh to shard_map over; single-device
    # runs have no replicas to diverge, so the flag degrades to a note
    device_probe = None
    if device_telemetry:
        if device_mesh is not None:
            from videop2p_tpu.obs import make_device_probe

            device_probe = make_device_probe(device_mesh)
            print(f"[p2p] device telemetry: probing {device_mesh.size} "
                  f"devices, divergence over {device_probe.divergence_axes}")
        else:
            print("[p2p] --device_telemetry needs --mesh — single-device "
                  "runs have no replicas to probe; flag ignored")

    unet_fn = program_set.unet_fn
    params = bundle.unet_params
    # the tuned pipeline's own scheduler config (incl. the steps_offset: 1 the
    # Stage-1 export writes), not hardcoded SD defaults (run_videop2p.py:101-114)
    sched = program_set.scheduler
    key = jax.random.key(seed)

    # ---- load + encode the video ----------------------------------------
    frames = load_frame_sequence(image_path, size=width, num_frames=video_len)
    video = program_set.frames_to_video(frames)  # (1,F,H,W,3) in [-1,1]
    with phase_timer("vae_encode"):
        # posterior mean, not a sample — inversion fidelity
        # (image2latent_video, run_videop2p.py:530-537); one jitted dispatch
        # through the shared instrumented vae_encode program
        latents = jax.block_until_ready(program_set.encode(video, key))
    if device_mesh is not None:
        from videop2p_tpu.parallel import latent_sharding

        # frames ride the sp axis; the inversion/edit jits below then compute
        # sequence-parallel with XLA-inserted collectives over ICI
        latents = jax.device_put(latents, latent_sharding(device_mesh))

    cond_src = program_set.encode_prompts([prompt])
    cond_all = program_set.encode_prompts(list(prompts))
    uncond = program_set.encode_prompts([""])[0]
    if multi:
        # per-frame conditioning: repeat each prompt embedding across frames
        # (the reference's `repeat(text_embeddings, 'b n c -> (b f) n c')`,
        # pipeline_tuneavideo.py:366-367); downstream consumers may then vary
        # embeddings per frame
        cond_all = jnp.repeat(cond_all[:, None], video_len, axis=1)

    # ---- controller (host-side; needed before inversion for the cached-
    # source capture windows) — shared construction with the serving
    # engine (the config's blend_word 2-list becomes ((src,), (edit,)),
    # run_videop2p.py:87-88)
    ctx = program_set.controller(
        list(prompts),
        is_word_swap=bool(is_word_swap),
        cross_replace_steps=cross_replace_steps,
        self_replace_steps=self_replace_steps,
        blend_word=blend_word,
        eq_params=eq_params,
        mask_th=MASK_TH,
    )

    # ---- DDIM inversion (+ null-text in full mode) ----------------------
    dep_w = dependent_weights if dependent_p2p else 0.0

    use_cached = cached_source and fast and eta == 0

    # persisted-products lookup: on a hit the inversion walk (and, when
    # present, the null-text optimization) is skipped. NOT consulted when
    # the cached-source fast mode is active: attention-map captures are
    # ~3 GB and not persisted, and flipping a repeat invocation onto the
    # live-source path would silently change its output (drifting source,
    # different controller base maps) — identical commands must produce
    # identical results. The trajectory is still SAVED by cached-mode runs
    # so a later full-mode run of the same clip skips its inversion.
    from videop2p_tpu.serve.store import (
        load_persisted_inversion,
        save_persisted_inversion,
    )
    from videop2p_tpu.utils.inv_cache import (
        content_fingerprint,
        inversion_cache_key,
    )

    # the disk layer's root: a shared --inv_store amortizes one inversion
    # across sweeps / output dirs (keys are content-addressed, so sharing
    # is always safe); default keeps the per-results-dir layout
    store_root = inv_store or output_folder

    inv_key = inversion_cache_key(
        image_path=os.path.abspath(image_path), prompt=prompt,
        steps=NUM_DDIM_STEPS, width=width, video_len=video_len,
        dependent_p2p=dependent_p2p, dependent_weights=dep_w,
        decay_rate=decay_rate, window_size=window_size, ar_sample=ar_sample,
        ar_coeff=ar_coeff, seed=seed,
        # content fingerprints, not path identity: re-tuning the checkpoint
        # in place or replacing the clip's frames must miss, not reuse
        checkpoint=content_fingerprint(pretrained_model_path),
        clip=content_fingerprint(image_path),
        tiny=tiny, guidance=GUIDANCE_SCALE,
        # the VAE-encode dtype changes the latents the trajectory starts from
        mixed_precision=mixed_precision,
    )
    # persistence is single-host/unsharded only: a sharded global trajectory
    # cannot be np.asarray'd from one process, and concurrent writers from a
    # multi-host mesh would race on the same entry
    reuse_inversion = reuse_inversion and mesh is None and jax.process_count() == 1


    if use_cached:
        from videop2p_tpu.pipelines.cached import capture_windows

        # outside these windows the gates multiply the base maps out
        # exactly, so nothing else needs capturing
        cross_len, self_window = capture_windows(ctx, NUM_DDIM_STEPS)

        from videop2p_tpu.pipelines.fast import capture_shapes, choose_cached_maps

        budget_gb = float(os.environ.get("VIDEOP2P_CACHED_MAPS_BUDGET_GB", "6"))

        # the shape check shares cached_fast_edit's OWN capture call, so the
        # budget always sizes exactly what the fused program will materialize
        def shapes_for(tm_dtype):
            return capture_shapes(
                unet_fn, params, sched, latents, cond_src, ctx,
                num_inference_steps=NUM_DDIM_STEPS,
                cross_len=cross_len, self_window=self_window,
                dependent_weight=dep_w,
                dependent_sampler=sampler if dep_w > 0 else None,
                temporal_maps_dtype=tm_dtype,
            )[1]

        # the budget is per chip: on a frame-sharded mesh the capture trees
        # shard over frames/spatial positions, so each chip holds 1/sp of
        # the global bytes — exactly what makes long-video cached mode fit;
        # when bf16 maps overflow, the decision escalates to float8 storage
        # for the (quadratic-in-frames) temporal tree before giving up
        sp_shard = int(mesh.split(",")[1]) if mesh else 1
        fits, tm_dtype, map_gb, per_chip_gb = choose_cached_maps(
            shapes_for, sp=sp_shard, budget_gb=budget_gb
        )
        if not fits:
            print(
                f"[p2p] cached-source maps need {per_chip_gb:.1f} GiB/chip "
                f"even with 1-byte temporal maps (> budget {budget_gb:.1f} "
                "GiB) — falling back to the live source stream"
            )
            use_cached = False
            if reuse_schedule != "off":
                print("[p2p] reuse_schedule disabled with it — the deep-"
                      "feature cache rides the cached edit scan")
                reuse_schedule = "off"
        else:
            print(
                f"[p2p] cached-source fast mode: cross window {cross_len} steps, "
                f"self window {self_window}, maps {map_gb:.2f} GiB global / "
                f"{per_chip_gb:.2f} GiB per chip"
                + (f", temporal maps stored {jnp.dtype(tm_dtype).name}"
                   if tm_dtype is not None else "")
            )

    # consult the persisted products only once the cached-source decision is
    # FINAL (incl. the maps-budget fallback): a budget-forced live run is
    # live on every invocation, so reuse keeps its output-identity guarantee
    # the persisted null embeddings are precision- AND mode-variant
    # products: a mixed/amortized run must never silently reuse fp32 or
    # optimized embeddings (or vice versa)
    null_tag = f"_i{num_inner_steps}" + (
        "_mixed" if null_text_precision == "mixed" else ""
    ) + ("" if null_text_mode == "optimize" else f"_{null_text_mode}")
    reused = (
        load_persisted_inversion(
            store_root, inv_key, want_null=not fast,
            null_tag=null_tag,
        )
        if reuse_inversion and not use_cached
        else None
    )

    key, ik = jax.random.split(key)
    null_embeddings = None
    out = None
    videos = None
    # {"inversion": rec, "edit": rec} when --attn_maps captured anything
    attn_records = {}
    if use_cached:
        # capture + controlled denoise as ONE device program (the shared
        # pipelines.cached_fast_edit — the same program bench.py measures):
        # a second dispatch costs a tunnel round trip (~0.5-1 s measured),
        # and the capture trees never surface as program outputs
        from videop2p_tpu.pipelines import cached_fast_edit

        print("Start Video-P2P!")
        t0 = time.perf_counter()
        with phase_timer("cached_invert_edit"), \
                maybe_trace("cached_invert_edit"):
            # capture-inversion + controlled edit + VAE decode, one program:
            # the chunked decode alone is 4 host dispatches when run eagerly,
            # each riding the tunnel; telemetry rides the SAME program's
            # scan outputs (scalars per step — bytes of extra output)
            def fused_to_video(p, vp, x, k):
                res = cached_fast_edit(
                    unet_fn, p, sched, x, cond_src, cond_all, uncond, ctx,
                    num_inference_steps=NUM_DDIM_STEPS,
                    guidance_scale=GUIDANCE_SCALE,
                    cross_len=cross_len, self_window=self_window,
                    dependent_weight=dep_w,
                    dependent_sampler=sampler if dep_w > 0 else None,
                    key=k,
                    temporal_maps_dtype=tm_dtype,
                    telemetry=telemetry,
                    device_probe=device_probe,
                    attn_maps=attn_maps,
                    reuse_schedule=reuse_schedule,
                )
                traj, edited = res[0], res[1]
                vids = decode_video(bundle.vae, vp, edited.astype(dtype), sequential=True)
                return (traj, (vids.astype(jnp.float32) + 1) / 2) + tuple(res[2:])

            res = instrumented_jit(fused_to_video, program="cached_invert_edit")(
                params, bundle.vae_params, latents, ik
            )
            traj, videos = res[0], res[1]
            extras = list(res[2:])
            videos = np.asarray(jax.device_get(videos))
            if telemetry:
                tel = extras.pop(0)
                if run_ledger is not None:
                    from videop2p_tpu.obs import (
                        decode_step_stats,
                        summarize_step_stats,
                    )

                    run_ledger.telemetry(
                        "cached_invert_edit",
                        {"summary": summarize_step_stats(tel),
                         "steps": decode_step_stats(tel)},
                    )
            if device_probe is not None:
                _ledger_device_stats(
                    run_ledger, "cached_invert_edit",
                    jax.device_get(extras.pop(0)), device_probe,
                )
            if attn_maps:
                attn_records = jax.device_get(extras.pop(0))
        if run_ledger is not None:
            # measured peak next to the program_analysis predicted peak-HBM
            # (the instrumented_jit cache miss above recorded it) — the
            # ledger summary renders predicted-vs-actual from these two
            run_ledger.memory_snapshot(note="after_cached_edit")
        print(f"[p2p] cached invert+edit+decode done in "
              f"{time.perf_counter() - t0:.1f}s")
        if reuse_inversion:
            save_persisted_inversion(
                store_root, inv_key, np.asarray(traj),
                meta={"image_path": image_path, "prompt": prompt,
                      "steps": NUM_DDIM_STEPS, "width": width,
                      "video_len": video_len, "fast": fast},
            )
    elif reused is not None:
        traj_np, null_np = reused
        print(f"[p2p] reusing persisted inversion products (key {inv_key}) — "
              "skipping DDIM inversion"
              + (" and null-text optimization" if null_np is not None else ""))
        traj = jnp.asarray(traj_np)
        x_t = traj[-1]
        if null_np is not None:
            null_embeddings = jnp.asarray(null_np)
    else:
        with phase_timer("ddim_inversion"):
            inv = instrumented_jit(
                lambda p, x, k: ddim_inversion(
                    unet_fn, p, sched, x, cond_src,
                    num_inference_steps=NUM_DDIM_STEPS,
                    dependent_weight=dep_w,
                    dependent_sampler=sampler if dep_w > 0 else None,
                    key=k,
                    attn_maps=attn_maps,
                ),
                program="ddim_inversion",
            )(params, latents, ik)
            if attn_maps:
                traj, inv_attn = inv
                attn_records["inversion"] = jax.device_get(inv_attn)
            else:
                traj = inv
            x_t = jax.block_until_ready(traj[-1])
        if reuse_inversion:
            save_persisted_inversion(
                store_root, inv_key, np.asarray(traj),
                meta={"image_path": image_path, "prompt": prompt,
                      "steps": NUM_DDIM_STEPS, "width": width,
                      "video_len": video_len, "fast": fast},
            )

    if not fast and null_embeddings is None:
        # the official mode exists for reference parity: null-text spends
        # minutes optimizing embeddings so the source stream approximately
        # reconstructs under CFG — the cached --fast mode reconstructs
        # EXACTLY at ~1/20th the cost (pipelines/cached.py)
        print("[p2p] note: --fast (cached-source) reconstructs the source "
              "exactly without null-text optimization")
        # loaded executables count against HBM: drop the inversion program
        # before compiling the null-text grad program, and that one before
        # the CFG edit (a 16 GB chip OOMs with all three resident)
        jax.clear_caches()
        key, nk = jax.random.split(key)
        # mixed precision: the inner loop's forwards/backward run on a
        # bf16-compute clone of the UNet over the SAME params; the fp32
        # islands (scheduler coefficients, Adam state, loss accumulation)
        # are the library's contract (pipelines/inversion.py)
        null_fn = unet_fn
        if null_text_precision == "mixed" and dtype != jnp.bfloat16:
            null_fn = make_unet_fn(bundle.unet.clone(dtype=jnp.bfloat16))
        null_stats = None
        null_kwargs = dict(
            num_inference_steps=NUM_DDIM_STEPS,
            guidance_scale=GUIDANCE_SCALE,
            num_inner_steps=num_inner_steps,
            null_text_precision=null_text_precision,
            null_text_mode=null_text_mode,
            dependent_weight=dep_w,
            dependent_sampler=sampler if dep_w > 0 else None,
            key=nk,
        )
        # phase unit count: inner Adam steps for optimize/hybrid (K=3), one
        # forward per outer step for the closed-form amortized mode
        per_outer = {"optimize": num_inner_steps, "hybrid": 3,
                     "amortized": 1}.get(null_text_mode, num_inner_steps)
        with phase_timer("null_text_optimization",
                         count=NUM_DDIM_STEPS * per_outer,
                         unit="inner-step"), \
             program_label("null_text_fused" if null_text_chunk == 0
                           else "null_text_chunked"):
            # program_label: the fused program jits inside its own cache, so
            # compile events are attributed here rather than per-jit-wrapper
            if null_text_chunk > 0:
                # watchdog fallback: short host-dispatched chunks
                null_embeddings = null_text_optimization(
                    null_fn, params, sched, traj, cond_src, uncond[None],
                    outer_chunk=null_text_chunk, telemetry=telemetry,
                    **null_kwargs,
                )
                if telemetry:
                    null_embeddings, null_tel = null_embeddings
                    null_stats = {"latent_stats": null_tel}
            else:
                # ONE jitted program, trajectory buffer donated (x_t was
                # extracted and the trajectory persisted above — nothing
                # reads it after this point)
                null_embeddings, null_stats = null_text_optimization_fused(
                    null_fn, params, sched, traj, cond_src, uncond[None],
                    donate=True, return_stats=True, telemetry=telemetry,
                    **null_kwargs,
                )
            null_embeddings = jax.block_until_ready(null_embeddings)
        if null_stats is not None and "inner_steps" in null_stats:
            inner_total = int(np.asarray(null_stats["inner_steps"]).sum())
            print(f"[p2p] null-text ({null_text_mode}/{null_text_precision}): "
                  f"{inner_total} inner Adam steps across {NUM_DDIM_STEPS} "
                  f"outer steps, final loss "
                  f"{float(np.asarray(null_stats['final_loss'])[-1]):.3e}")
        if run_ledger is not None and null_stats is not None:
            from videop2p_tpu.obs import decode_null_text_stats, summarize_step_stats

            if "inner_steps" in null_stats:
                run_ledger.telemetry(
                    "null_text_fused", decode_null_text_stats(null_stats)
                )
            elif null_stats.get("latent_stats") is not None:
                run_ledger.telemetry(
                    "null_text_chunked",
                    {"latent": summarize_step_stats(null_stats["latent_stats"])},
                )
            run_ledger.memory_snapshot(note="after_null_text")
        if reuse_inversion:
            # trajectory.npy was written after inversion — only the null
            # embeddings are new here
            save_persisted_inversion(
                store_root, inv_key, None,
                np.asarray(null_embeddings), null_tag=null_tag,
            )
        jax.clear_caches()

    # ---- controlled denoise (skipped when the fused cached path already
    # produced the decoded videos above) ----------------------------------
    if videos is None:
        print("Start Video-P2P!")
        key, ek = jax.random.split(key)
        t0 = time.perf_counter()
        with phase_timer("edit_sample"), maybe_trace("edit_sample"):
            out = instrumented_jit(
                lambda p, x, u, k: edit_sample(
                    unet_fn, p, sched, x, cond_all, u,
                    num_inference_steps=NUM_DDIM_STEPS,
                    guidance_scale=GUIDANCE_SCALE,
                    ctx=ctx,
                    source_uses_cfg=not fast,
                    eta=eta,
                    key=k,
                    dependent_sampler=sampler if (dependent_p2p and eta > 0) else None,
                    null_uncond_embeddings=null_embeddings,
                    telemetry=telemetry,
                    device_probe=device_probe,
                    attn_maps=attn_maps,
                ),
                program="edit_sample",
            )(params, x_t, uncond, ek)
            if telemetry or device_probe is not None or attn_maps:
                out, *edit_extras = out
                if telemetry:
                    edit_tel = edit_extras.pop(0)
                if device_probe is not None:
                    _ledger_device_stats(
                        run_ledger, "edit_sample",
                        jax.device_get(edit_extras.pop(0)), device_probe,
                    )
                if attn_maps:
                    attn_records["edit"] = jax.device_get(edit_extras.pop(0))
            out = jax.block_until_ready(out)
        print(f"[p2p] controlled denoise done in {time.perf_counter() - t0:.1f}s")
        if telemetry and run_ledger is not None:
            from videop2p_tpu.obs import decode_step_stats, summarize_step_stats

            run_ledger.telemetry(
                "edit_sample",
                {"summary": summarize_step_stats(edit_tel),
                 "steps": decode_step_stats(edit_tel)},
            )
        if run_ledger is not None:
            run_ledger.memory_snapshot(note="after_edit")

        # drop the edit executable before compiling the decode program — at
        # fp32 full scale the two do not fit the chip together
        jax.clear_caches()
        with phase_timer("vae_decode"):
            # one jitted dispatch, rescale included — the shared
            # instrumented vae_decode program (serve/programs.py)
            videos = np.asarray(jax.device_get(program_set.decode(out)))

    # stream 0 = inversion reconstruction, stream 1 = edit
    # (run_videop2p.py:688-701; duration 250 ms/frame = 4 fps)
    save_video_gif(videos[0], inversion_gif, fps=4)
    save_video_gif(videos[1], edit_gif, fps=4)
    print(f"[p2p] wrote {inversion_gif} and {edit_gif}")

    # semantic observability (ISSUE 4): attention sidecar + quality
    # metrics + regression verdicts + the self-contained HTML report
    report_path = None
    if run_ledger is not None and (attn_records or quality or report):
        report_path = _semantic_obs(
            run_ledger,
            output_folder=output_folder, save_name=save_name, suffix=suffix,
            prompts=list(prompts), tokenizer=bundle.tokenizer,
            attn_records=attn_records,
            # which prompt stream each capture's heat axis holds: the
            # inversion walk sees only the source; the cached edit batch
            # drops the source stream, the live edit keeps all P
            stream_map={
                "inversion": [0],
                "edit": (list(range(1, len(prompts))) if use_cached
                         else list(range(len(prompts)))),
            },
            quality=quality, report=report,
            source01=np.asarray(jax.device_get((video[0] + 1.0) / 2.0)),
            videos=videos,
        )

    if run_ledger is not None:
        run_ledger.event("artifacts", inversion_gif=inversion_gif,
                         edit_gif=edit_gif, report=report_path)
        run_ledger.memory_snapshot(note="run_end")
        run_ledger.close()
        print(f"[p2p] run ledger: {run_ledger.path}")
    return inversion_gif, edit_gif


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--config", type=str, default="./configs/videop2p.yaml")
    parser.add_argument("--fast", action="store_true")
    parser.add_argument("--dependent_p2p", default=False, action="store_true")
    parser.add_argument("--tiny", action="store_true",
                        help="random-init tiny models (weightless smoke mode)")
    parser.add_argument("--mesh", type=str, default=None,
                        help="device mesh dp,sp,tp (e.g. 1,4,1: frames over 4 chips)")
    parser.add_argument("--multi", action="store_true",
                        help="per-frame text-embedding mode")
    parser.add_argument("--live_source", action="store_true",
                        help="keep the live source stream in fast mode "
                             "(disable the cached-source replay)")
    parser.add_argument("--no_reuse_inversion", action="store_true",
                        help="do not persist/reuse inversion products "
                             "(trajectory + null embeddings) across runs")
    parser.add_argument("--inv_store", type=str, default=None,
                        help="shared content-addressed root for persisted "
                             "inversion products (serve/store.py disk "
                             "layer) — sweeps amortize one inversion per "
                             "clip across cells; default keeps the "
                             "per-results-dir layout")
    parser.add_argument("--mixed_precision", type=str, default=None,
                        choices=["fp32", "no", "fp16", "bf16"],
                        help="model compute dtype (default fp32 = the "
                             "reference's Stage-2 behavior; bf16 runs the "
                             "MXU at full rate — ~3.5x faster end-to-end)")
    parser.add_argument("--quant_mode", type=str, default="off",
                        choices=["off", "w8", "w8a8"],
                        help="UNet weight quantization at load (--fast "
                             "only): w8 = int8 weights + per-output-channel "
                             "scales stored 1-byte and dequantized inside "
                             "the traced program; w8a8 adds activation "
                             "fake-quant at the attention Dense boundaries")
    parser.add_argument("--reuse_schedule", type=str, default="off",
                        help="cross-step deep-feature reuse in the cached "
                             "fast edit ('uniform:K' or "
                             "'custom:<p0,p1,...>'): listed steps run the "
                             "full UNet, the rest reuse the cached deep "
                             "feature through a shallow path — one compiled "
                             "program either way")
    add_dependent_args(parser)
    add_null_text_args(parser)
    add_obs_args(parser)
    args = parser.parse_args()
    # multi-host: join the process group before any device use (no-op on a
    # single host; see parallel/distributed.py)
    from videop2p_tpu.parallel import initialize_distributed

    initialize_distributed()
    cfg = load_config(args.config)
    # flags win over config for the keys both surfaces expose
    args.multi = args.multi or bool(cfg.pop("multi", False))
    if args.mixed_precision is not None:
        cfg["mixed_precision"] = args.mixed_precision
    if args.null_text_precision is not None:
        cfg["null_text_precision"] = args.null_text_precision
    if args.null_text_chunk is not None:
        cfg["null_text_chunk"] = args.null_text_chunk
    if args.null_text_mode is not None:
        cfg["null_text_mode"] = args.null_text_mode
    args.mesh = args.mesh or cfg.pop("mesh", None)
    main(
        **cfg,
        fast=args.fast,
        dependent=args.dependent,
        dependent_p2p=args.dependent_p2p,
        num_frames=args.num_frames,
        decay_rate=args.decay_rate,
        window_size=args.window_size,
        ar_sample=args.ar_sample,
        ar_coeff=args.ar_coeff,
        eta=args.eta,
        dependent_weights=args.dependent_weights,
        tiny=args.tiny,
        mesh=args.mesh,
        multi=args.multi,
        cached_source=not args.live_source,
        quant_mode=args.quant_mode,
        reuse_schedule=args.reuse_schedule,
        reuse_inversion=not args.no_reuse_inversion,
        inv_store=args.inv_store,
        telemetry=args.telemetry,
        ledger=args.ledger,
        program_analysis=not args.no_program_analysis,
        attn_maps=args.attn_maps,
        quality=args.quality,
        report=args.report,
        device_telemetry=args.device_telemetry,
        latency=args.latency,
        trace_analysis=args.trace_analysis,
        incidents=args.incidents,
    )
