"""Video transformer block: frame (spatial), text-cross and temporal attention.

TPU-native re-design of /root/reference/tuneavideo/models/attention.py. Key
behaviors preserved:

  * ``attn1`` is **FrameAttention** — spatial self-attention where every
    frame's keys/values come from frame 0 only (attention.py:296-302). This is
    the big hw×hw attention; it is NOT a controlled site (the reference's
    monkey-patch only rebinds modules named ``CrossAttention``,
    ptp_utils.py:236-239).
  * ``attn2`` is text cross-attention — a controlled site (``is_cross=True``).
  * ``attn_temp`` is temporal self-attention over the frame axis with a
    **zero-initialized output projection** (attention.py:196-202) so the
    2-D→3-D inflation starts as the identity — a controlled site
    (``is_cross=False``; see SURVEY §3.4 subtlety 1).

Control is a pure function applied to materialized attention probabilities
(:func:`videop2p_tpu.control.control_attention`) instead of a monkey-patched
forward; sites also ``sow`` head-averaged probability maps into the
``attn_store`` collection (the reference's ``AttentionStore``,
run_videop2p.py:248-284) when the caller makes that collection mutable.

Batch layout matches the reference's fold order so the control layer can
factor the batch axis: frames fold batch-major ``(B, F, …) → (B·F, …)`` for
spatial/cross sites, spatial positions fold batch-major ``(B·N, F, C)`` for
the temporal site (attention.py:94, :262-268).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn
from flax import struct

from videop2p_tpu.control.controllers import ControlContext, control_attention
from videop2p_tpu.models.layers import TpuGroupNorm

__all__ = [
    "AttnControl",
    "FrameAttention",
    "ControlledAttention",
    "FeedForward",
    "BasicTransformerBlock",
    "Transformer3DModel",
]

Dtype = jnp.dtype


class AttnControl(struct.PyTreeNode):
    """Bundle threaded through the UNet forward: the edit context plus the
    (traced) step index of the enclosing sampling scan. Replaces the
    reference's hidden ``cur_step``/``cur_att_layer`` counters
    (run_videop2p.py:212-224)."""

    ctx: Optional[ControlContext]
    step_index: jax.Array  # () int32
    # uncond streams ahead of the ctx.num_prompts cond streams in the batch;
    # -1 → ctx.num_prompts (the symmetric CFG layout). Fast mode drops the
    # source stream's unused uncond forward (num_uncond = num_prompts − 1).
    num_uncond: int = struct.field(pytree_node=False, default=-1)
    # capture mode (cached-source fast edit): sow the FULL per-head
    # probabilities at every controlled site into the ``attn_base`` collection
    # — used during DDIM inversion so the edit can replay the source stream's
    # maps without re-running its forwards
    capture: bool = struct.field(pytree_node=False, default=False)
    # cached-source mode: nested {module-path: {"probs": map}} tree giving the
    # source stream's maps for THIS step; the batch holds only the P−1 edit
    # streams and each controlled site reads its base map here. A site type
    # with an empty capture window is absent from the tree — its gate is
    # inactive at every step, so the site skips the edit entirely (the
    # ``cached_source`` flag below keeps the layout contract unambiguous
    # even when BOTH windows are empty and the tree is None).
    cached_base: Optional[dict] = None
    cached_source: bool = struct.field(pytree_node=False, default=False)

    def base_map_for(self, path) -> Optional[jax.Array]:
        """Look up this site's cached source map by its flax module path."""
        node = self.cached_base
        if node is None:
            return None
        for name in path:
            if not isinstance(node, dict) or name not in node:
                return None
            node = node[name]
        leaf = node.get("probs") if isinstance(node, dict) else None
        if isinstance(leaf, tuple):  # flax sow stacks values into a tuple
            leaf = leaf[0]
        return leaf


def _split_heads(x: jax.Array, heads: int) -> jax.Array:
    """(B, N, H·D) → (B, H, N, D)"""
    b, n, _ = x.shape
    return x.reshape(b, n, heads, -1).transpose(0, 2, 1, 3)


def _merge_heads(x: jax.Array) -> jax.Array:
    """(B, H, N, D) → (B, N, H·D)"""
    b, h, n, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b, n, h * d)


def _aq(fn: Optional[Callable], x: jax.Array) -> jax.Array:
    """Apply an activation fake-quant seam (``w8a8`` quant mode —
    models/quant.py ``fake_quant_act``) to a Dense input; identity when no
    seam is wired, so the off path's program is byte-identical."""
    return x if fn is None else fn(x)


def _stable_softmax(sim: jax.Array, dtype: Dtype) -> jax.Array:
    """Softmax in float32 regardless of compute dtype (the reference's
    exp(sim−max)/Σ stabilization, ptp_utils.py:217).

    An all-bf16 variant (f32 only in the streaming row-sum) was measured on
    v5e and came out ~4 % SLOWER end-to-end — XLA already streams the
    convert+reduce without materializing f32 — so the f32 form stays.
    """
    return jax.nn.softmax(sim.astype(jnp.float32), axis=-1).astype(dtype)


class FrameAttention(nn.Module):
    """Spatial self-attention with frame-0 keys/values
    (reference ``FrameAttention``, attention.py:239-328).

    Input: (B, F, N, C) with N = H·W spatial positions. Queries come from
    every frame; keys/values from frame 0 only — O(F·N²) with a shared KV,
    which on TPU is one batched MXU matmul per projection. The computed
    ``former_frame_index`` in the reference is dead code (attention.py:293-294);
    Video-P2P uses first-frame attention, not sparse-causal [first, former].

    ``attention_fn`` lets callers swap the inner softmax-attention for a
    fused Pallas flash kernel (ops.flash_attention); signature
    ``(q, k, v) -> out`` with shapes (B, F, H, N, D), (B, H, N, D) ×2.
    """

    heads: int
    dim_head: int
    dtype: Dtype = jnp.float32
    attention_fn: Optional[Callable[[jax.Array, jax.Array, jax.Array], jax.Array]] = None
    # explicit Megatron row-parallel output projection: a ``dot_general``
    # replacement for the to_out matmul (parallel.make_megatron_out_dot —
    # psum_scatter over the token axis instead of the all-reduce GSPMD
    # inserts when the kernel's rows shard over ``tensor``)
    row_parallel_dot: Optional[Callable] = None
    # activation fake-quant at the Dense boundaries (w8a8 quant mode);
    # None → byte-identical off path (same seam pattern as row_parallel_dot)
    act_quant_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        b, f, n, _ = x.shape
        inner = self.heads * self.dim_head
        x = _aq(self.act_quant_fn, x)
        q = nn.Dense(inner, use_bias=False, dtype=self.dtype, name="to_q")(x)
        kv_src = x[:, 0]  # frame-0 KV (attention.py:296-302)
        k = nn.Dense(inner, use_bias=False, dtype=self.dtype, name="to_k")(kv_src)
        v = nn.Dense(inner, use_bias=False, dtype=self.dtype, name="to_v")(kv_src)

        q = q.reshape(b, f, n, self.heads, self.dim_head).transpose(0, 1, 3, 2, 4)
        k = _split_heads(k, self.heads)
        v = _split_heads(v, self.heads)

        if self.attention_fn is not None:
            out = self.attention_fn(q, k, v)
        else:
            scale = self.dim_head ** -0.5
            sim = jnp.einsum("bfhqd,bhkd->bfhqk", q, k) * scale
            probs = _stable_softmax(sim, self.dtype)
            out = jnp.einsum("bfhqk,bhkd->bfhqd", probs, v)

        out = out.transpose(0, 1, 3, 2, 4).reshape(b, f, n, inner)
        rp = ({"dot_general": self.row_parallel_dot}
              if self.row_parallel_dot is not None else {})
        out = _aq(self.act_quant_fn, out)
        return nn.Dense(inner, dtype=self.dtype, name="to_out", **rp)(out)


class ControlledAttention(nn.Module):
    """Multi-head attention with materialized, editable probabilities.

    ``site`` is ``"cross"`` (text cross-attention) or ``"temporal"`` (frame
    self-attention). Probabilities are (B, H, Q, K); when an
    :class:`AttnControl` is supplied they pass through the pure edit
    ``control_attention`` (the reference's patched ``attn =
    controller(attn, …)`` seam, ptp_utils.py:218); head-averaged pre-edit maps
    are sown into the ``attn_store`` collection when Q ≤ 32² (the reference's
    store guard, run_videop2p.py:257).
    """

    heads: int
    dim_head: int
    site: str  # "cross" | "temporal"
    zero_init_out: bool = False
    dtype: Dtype = jnp.float32
    # sequence-parallel kernel for UNCONTROLLED passes, e.g. ring attention
    # over a sharded frame axis ((q, k, v) (B, H, N, D) → out). Controlled
    # passes need materialized probabilities (SURVEY §7 hard-part 2), so a
    # non-None ``control`` always takes the dense path.
    attention_fn: Optional[Callable[[jax.Array, jax.Array, jax.Array], jax.Array]] = None
    # explicit Megatron row-parallel to_out (see FrameAttention); the block
    # threads it to the CROSS site only — the temporal site's token axis is
    # the frame axis, which belongs to the ``frames`` mesh axis
    row_parallel_dot: Optional[Callable] = None
    # activation fake-quant at the Dense boundaries (see FrameAttention)
    act_quant_fn: Optional[Callable] = None

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        context: Optional[jax.Array] = None,
        control: Optional[AttnControl] = None,
        video_length: Optional[int] = None,
    ) -> jax.Array:
        inner = self.heads * self.dim_head
        x = _aq(self.act_quant_fn, x)
        ctx_in = x if context is None else _aq(self.act_quant_fn, context)

        q = nn.Dense(inner, use_bias=False, dtype=self.dtype, name="to_q")(x)
        k = nn.Dense(inner, use_bias=False, dtype=self.dtype, name="to_k")(ctx_in)
        v = nn.Dense(inner, use_bias=False, dtype=self.dtype, name="to_v")(ctx_in)
        q, k, v = (_split_heads(t, self.heads) for t in (q, k, v))

        if self.attention_fn is not None and control is None:
            out = self.attention_fn(q, k, v)
            out = _aq(self.act_quant_fn, _merge_heads(out))
            return nn.Dense(inner, dtype=self.dtype, name="to_out",
                            **self._out_kwargs())(out)

        sim = jnp.einsum("bhqd,bhkd->bhqk", q, k) * (self.dim_head ** -0.5)
        probs = _stable_softmax(sim, self.dtype)

        if probs.shape[-2] <= 1024:
            # pre-edit store (AttentionControlEdit stores before editing,
            # run_videop2p.py:304-305); head-mean commutes with LocalBlend's
            # word-sum + site-mean (see control/local_blend.py).
            self.sow("attn_store", "maps", probs.mean(axis=1))

        if control is not None and control.capture:
            # cached-source capture (inversion pass): full per-head pre-edit
            # probabilities, every controlled site — the edit's base maps.
            # Stored in bf16 regardless of compute dtype: base maps are
            # semantic layout guides already one trajectory position off a
            # live source stream, and halving the cache is what keeps fp32
            # runs inside the HBM budget (6.2 → 3.1 GiB at SD 8-frame scale)
            self.sow("attn_base", "probs", probs.astype(jnp.bfloat16))

        if control is not None:
            if video_length is None:
                if self.site != "temporal":
                    # at cross sites x is frame-folded (B·F, N, C): N is the
                    # spatial-token count, not the frame count — require it
                    raise ValueError("video_length is required at controlled cross sites")
                video_length = x.shape[1]
            base_map = control.base_map_for(self.path)
            if control.cached_source and base_map is None:
                # cached-source batch (no source stream) at a site whose
                # capture window is empty: the gate is inactive at every
                # step, so the unedited probabilities are exactly right —
                # and the live-layout reshape below would mis-factor the
                # P−1-stream batch
                pass
            else:
                probs = control_attention(
                    probs,
                    control.ctx,
                    is_cross=(self.site == "cross"),
                    step_index=control.step_index,
                    video_length=video_length,
                    num_uncond=control.num_uncond,
                    base_map=base_map,
                )

        out = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
        out = _aq(self.act_quant_fn, _merge_heads(out))
        return nn.Dense(inner, dtype=self.dtype, name="to_out",
                        **self._out_kwargs())(out)

    def _out_kwargs(self) -> dict:
        kwargs = {}
        if self.zero_init_out:
            kwargs["kernel_init"] = nn.initializers.zeros
        if self.row_parallel_dot is not None:
            kwargs["dot_general"] = self.row_parallel_dot
        return kwargs


class FeedForward(nn.Module):
    """GEGLU feed-forward (diffusers ``FeedForward``/``GEGLU`` the reference
    block uses, attention.py:190)."""

    dim: int
    mult: int = 4
    dtype: Dtype = jnp.float32
    # explicit Megatron row-parallel proj_out (see FrameAttention)
    row_parallel_dot: Optional[Callable] = None
    # activation fake-quant at the Dense boundaries (see FrameAttention)
    act_quant_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        inner = self.dim * self.mult
        h = nn.Dense(inner * 2, dtype=self.dtype, name="proj_geglu")(
            _aq(self.act_quant_fn, x)
        )
        h, gate = jnp.split(h, 2, axis=-1)
        h = h * nn.gelu(gate)
        rp = ({"dot_general": self.row_parallel_dot}
              if self.row_parallel_dot is not None else {})
        h = _aq(self.act_quant_fn, h)
        return nn.Dense(self.dim, dtype=self.dtype, name="proj_out", **rp)(h)


class BasicTransformerBlock(nn.Module):
    """frame-attn → text-cross-attn → FF → temporal-attn, all pre-LayerNorm
    with residuals (reference BasicTransformerBlock, attention.py:140-268;
    execution order :233-268)."""

    dim: int
    heads: int
    dim_head: int
    dtype: Dtype = jnp.float32
    frame_attention_fn: Optional[Callable] = None
    # sequence-parallel temporal kernel (ring attention) for uncontrolled
    # passes over a sharded frame axis
    temporal_attention_fn: Optional[Callable] = None
    # explicit Megatron row-parallel outputs: threaded to the SPATIAL sites
    # (frame attn, cross attn, FF) whose token axis is free for the
    # psum_scatter; the temporal site's tokens are frames — that axis
    # belongs to the ``frames`` mesh axis and stays declarative
    row_parallel_dot: Optional[Callable] = None
    # activation fake-quant at every Dense boundary (w8a8 quant mode)
    act_quant_fn: Optional[Callable] = None

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        context: Optional[jax.Array] = None,
        control: Optional[AttnControl] = None,
    ) -> jax.Array:
        b, f, n, c = x.shape

        h = nn.LayerNorm(dtype=self.dtype, name="norm1")(x)
        x = x + FrameAttention(
            heads=self.heads, dim_head=self.dim_head, dtype=self.dtype,
            attention_fn=self.frame_attention_fn,
            row_parallel_dot=self.row_parallel_dot,
            act_quant_fn=self.act_quant_fn, name="attn1",
        )(h)

        if context is not None:
            # fold frames into batch, batch-major; repeat text per frame
            # (attention.py:94-95). Per-frame context (B, F, 77, D) is the
            # pipeline's "multi" embedding mode (pipeline_tuneavideo.py:366-367).
            h = nn.LayerNorm(dtype=self.dtype, name="norm2")(x).reshape(b * f, n, c)
            if context.ndim == 3:
                ctx_flat = jnp.repeat(context, f, axis=0)
            else:
                ctx_flat = context.reshape(b * f, *context.shape[2:])
            attn2 = ControlledAttention(
                heads=self.heads, dim_head=self.dim_head, site="cross",
                dtype=self.dtype, row_parallel_dot=self.row_parallel_dot,
                act_quant_fn=self.act_quant_fn, name="attn2",
            )(h, context=ctx_flat, control=control, video_length=f)
            x = x + attn2.reshape(b, f, n, c)

        x = x + FeedForward(self.dim, dtype=self.dtype,
                            row_parallel_dot=self.row_parallel_dot,
                            act_quant_fn=self.act_quant_fn, name="ff")(
            nn.LayerNorm(dtype=self.dtype, name="norm3")(x)
        )

        # temporal attention over the frame axis: (B, F, N, C) → (B·N, F, C),
        # batch-major over spatial positions (attention.py:262-268)
        h = nn.LayerNorm(dtype=self.dtype, name="norm_temp")(x)
        h = h.transpose(0, 2, 1, 3).reshape(b * n, f, c)
        attn_temp = ControlledAttention(
            heads=self.heads, dim_head=self.dim_head, site="temporal",
            zero_init_out=True, dtype=self.dtype,
            attention_fn=self.temporal_attention_fn,
            act_quant_fn=self.act_quant_fn, name="attn_temp",
        )(h, control=control, video_length=f)
        x = x + attn_temp.reshape(b, n, f, c).transpose(0, 2, 1, 3)
        return x


class Transformer3DModel(nn.Module):
    """GroupNorm → proj_in → transformer blocks → proj_out, with residual
    (reference Transformer3DModel, attention.py:32-137). Operates on
    (B, F, H, W, C); spatial positions flatten to a token axis internally."""

    heads: int
    dim_head: int
    depth: int = 1
    norm_groups: int = 32
    dtype: Dtype = jnp.float32
    gn_impl: str = "auto"
    group_norm_fn: Optional[Callable] = None
    frame_attention_fn: Optional[Callable] = None
    temporal_attention_fn: Optional[Callable] = None
    row_parallel_dot: Optional[Callable] = None
    act_quant_fn: Optional[Callable] = None

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        context: Optional[jax.Array] = None,
        control: Optional[AttnControl] = None,
    ) -> jax.Array:
        b, f, hh, ww, c = x.shape
        inner = self.heads * self.dim_head
        residual = x

        # fold frames into batch BEFORE the norm: the reference normalizes per
        # frame (rearrange precedes self.norm, attention.py:94-101), whereas
        # GroupNorm on (B, F, H, W, C) would pool statistics across frames
        h = x.reshape(b * f, hh, ww, c)
        h = TpuGroupNorm(
            num_groups=self.norm_groups, epsilon=1e-6, dtype=self.dtype,
            impl=self.gn_impl, group_norm_fn=self.group_norm_fn, name="norm",
        )(h)
        h = h.reshape(b, f, hh, ww, c)
        # use_linear_projection=False in SD1.x is a 1×1 conv — identical to a
        # Dense in channels-last layout (attention.py:74-81)
        h = nn.Dense(inner, dtype=self.dtype, name="proj_in")(
            _aq(self.act_quant_fn, h)
        )
        h = h.reshape(b, f, hh * ww, inner)

        for i in range(self.depth):
            h = BasicTransformerBlock(
                dim=inner, heads=self.heads, dim_head=self.dim_head,
                dtype=self.dtype, frame_attention_fn=self.frame_attention_fn,
                temporal_attention_fn=self.temporal_attention_fn,
                row_parallel_dot=self.row_parallel_dot,
                act_quant_fn=self.act_quant_fn,
                name=f"blocks_{i}",
            )(h, context=context, control=control)

        h = h.reshape(b, f, hh, ww, inner)
        rp = ({"dot_general": self.row_parallel_dot}
              if self.row_parallel_dot is not None else {})
        h = nn.Dense(c, dtype=self.dtype, name="proj_out", **rp)(
            _aq(self.act_quant_fn, h)
        )
        return h + residual
