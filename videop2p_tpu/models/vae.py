"""AutoencoderKL (the SD image VAE) in flax, applied per frame.

The reference consumes ``diffusers.AutoencoderKL`` as a frozen dependency
(/root/reference/run_tuning.py:130, run_videop2p.py:108-110): frames fold into
the batch for encode (run_tuning.py:282-287, run_videop2p.py:530-537) and
decode runs in chunks of 4 to bound memory (pipeline_tuneavideo.py:239-246).
This is a from-scratch flax implementation of the same architecture
(SD-1.x config: 128/256/512/512 channels, 2 resnets per level, mid attention,
latent scaling 0.18215 applied by callers), channels-last.

``encode_video``/``decode_video`` own the frame folding and decode chunking.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

__all__ = ["VAEConfig", "AutoencoderKL", "encode_video", "decode_video"]

Dtype = jnp.dtype


@dataclasses.dataclass(frozen=True)
class VAEConfig:
    in_channels: int = 3
    out_channels: int = 3
    latent_channels: int = 4
    block_out_channels: Tuple[int, ...] = (128, 256, 512, 512)
    layers_per_block: int = 2
    norm_num_groups: int = 32
    scaling_factor: float = 0.18215

    @classmethod
    def tiny(cls, **overrides) -> "VAEConfig":
        cfg = dict(block_out_channels=(8, 16), layers_per_block=1, norm_num_groups=4)
        cfg.update(overrides)
        return cls(**cfg)


class _ResnetBlock(nn.Module):
    """VAE resnet: GN → SiLU → conv → GN → SiLU → conv (no time emb)."""

    features: int
    groups: int
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        h = nn.GroupNorm(num_groups=self.groups, epsilon=1e-6, dtype=self.dtype, name="norm1")(x)
        h = nn.silu(h)
        h = nn.Conv(self.features, (3, 3), padding=1, dtype=self.dtype, name="conv1")(h)
        h = nn.GroupNorm(num_groups=self.groups, epsilon=1e-6, dtype=self.dtype, name="norm2")(h)
        h = nn.silu(h)
        h = nn.Conv(self.features, (3, 3), padding=1, dtype=self.dtype, name="conv2")(h)
        if x.shape[-1] != self.features:
            x = nn.Conv(self.features, (1, 1), dtype=self.dtype, name="conv_shortcut")(x)
        return x + h


class _AttnBlock(nn.Module):
    """Single-head spatial self-attention at the VAE mid block."""

    groups: int
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        b, h, w, c = x.shape
        res = x
        x = nn.GroupNorm(num_groups=self.groups, epsilon=1e-6, dtype=self.dtype, name="group_norm")(x)
        x = x.reshape(b, h * w, c)
        q = nn.Dense(c, dtype=self.dtype, name="to_q")(x)
        k = nn.Dense(c, dtype=self.dtype, name="to_k")(x)
        v = nn.Dense(c, dtype=self.dtype, name="to_v")(x)
        sim = jnp.einsum("bqc,bkc->bqk", q, k) * (c ** -0.5)
        probs = jax.nn.softmax(sim.astype(jnp.float32), axis=-1).astype(self.dtype)
        out = jnp.einsum("bqk,bkc->bqc", probs, v)
        out = nn.Dense(c, dtype=self.dtype, name="to_out")(out)
        return res + out.reshape(b, h, w, c)


class Encoder(nn.Module):
    config: VAEConfig
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.config
        g = cfg.norm_num_groups
        x = nn.Conv(cfg.block_out_channels[0], (3, 3), padding=1, dtype=self.dtype, name="conv_in")(x)
        for i, ch in enumerate(cfg.block_out_channels):
            for j in range(cfg.layers_per_block):
                x = _ResnetBlock(ch, g, self.dtype, name=f"down_{i}_resnets_{j}")(x)
            if i < len(cfg.block_out_channels) - 1:
                # diffusers pads asymmetrically ((0,1),(0,1)) before the
                # stride-2 conv (Downsample2D pad=0 path)
                x = jnp.pad(x, ((0, 0), (0, 1), (0, 1), (0, 0)))
                x = nn.Conv(
                    ch, (3, 3), strides=(2, 2), padding="VALID", dtype=self.dtype,
                    name=f"down_{i}_downsample",
                )(x)
        ch = cfg.block_out_channels[-1]
        x = _ResnetBlock(ch, g, self.dtype, name="mid_resnets_0")(x)
        x = _AttnBlock(g, self.dtype, name="mid_attn")(x)
        x = _ResnetBlock(ch, g, self.dtype, name="mid_resnets_1")(x)
        x = nn.GroupNorm(num_groups=g, epsilon=1e-6, dtype=self.dtype, name="conv_norm_out")(x)
        x = nn.silu(x)
        return nn.Conv(
            2 * cfg.latent_channels, (3, 3), padding=1, dtype=self.dtype, name="conv_out"
        )(x)


class Decoder(nn.Module):
    config: VAEConfig
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, z: jax.Array) -> jax.Array:
        cfg = self.config
        g = cfg.norm_num_groups
        rev = tuple(reversed(cfg.block_out_channels))
        x = nn.Conv(rev[0], (3, 3), padding=1, dtype=self.dtype, name="conv_in")(z)
        x = _ResnetBlock(rev[0], g, self.dtype, name="mid_resnets_0")(x)
        x = _AttnBlock(g, self.dtype, name="mid_attn")(x)
        x = _ResnetBlock(rev[0], g, self.dtype, name="mid_resnets_1")(x)
        for i, ch in enumerate(rev):
            for j in range(cfg.layers_per_block + 1):
                x = _ResnetBlock(ch, g, self.dtype, name=f"up_{i}_resnets_{j}")(x)
            if i < len(rev) - 1:
                b, hh, ww, c = x.shape
                x = jax.image.resize(x, (b, hh * 2, ww * 2, c), method="nearest")
                x = nn.Conv(ch, (3, 3), padding=1, dtype=self.dtype, name=f"up_{i}_upsample")(x)
        x = nn.GroupNorm(num_groups=g, epsilon=1e-6, dtype=self.dtype, name="conv_norm_out")(x)
        x = nn.silu(x)
        return nn.Conv(cfg.out_channels, (3, 3), padding=1, dtype=self.dtype, name="conv_out")(x)


class AutoencoderKL(nn.Module):
    """encode → (mean, logvar); decode(z) → image. Latent scaling is the
    caller's job (×scaling_factor after sampling, ÷ before decode — the
    reference's 0.18215 at run_videop2p.py:536 / :507)."""

    config: VAEConfig
    dtype: Dtype = jnp.float32

    def setup(self):
        self.encoder = Encoder(self.config, self.dtype)
        self.decoder = Decoder(self.config, self.dtype)
        self.quant_conv = nn.Conv(2 * self.config.latent_channels, (1, 1), dtype=self.dtype)
        self.post_quant_conv = nn.Conv(self.config.latent_channels, (1, 1), dtype=self.dtype)

    def encode(self, x: jax.Array) -> Tuple[jax.Array, jax.Array]:
        moments = self.quant_conv(self.encoder(x))
        mean, logvar = jnp.split(moments, 2, axis=-1)
        return mean, jnp.clip(logvar, -30.0, 20.0)

    def decode(self, z: jax.Array) -> jax.Array:
        return self.decoder(self.post_quant_conv(z))

    def __call__(self, x: jax.Array, key: jax.Array) -> jax.Array:
        mean, logvar = self.encode(x)
        z = mean + jnp.exp(0.5 * logvar) * jax.random.normal(key, mean.shape, mean.dtype)
        return self.decode(z)


def encode_video(
    vae: AutoencoderKL,
    params,
    video: jax.Array,
    key: jax.Array,
    *,
    sample: bool = True,
) -> jax.Array:
    """(B, F, H, W, 3) in [-1, 1] → scaled latents (B, F, H/8, W/8, 4).

    Frames fold into batch (run_tuning.py:282-287); posterior is sampled
    during training (latent_dist.sample, run_tuning.py:285) and taken at the
    mean for inversion fidelity when ``sample=False``.
    """
    b, f = video.shape[:2]
    flat = video.reshape((b * f,) + video.shape[2:])
    mean, logvar = vae.apply(params, flat, method=vae.encode)
    if sample:
        z = mean + jnp.exp(0.5 * logvar) * jax.random.normal(key, mean.shape, mean.dtype)
    else:
        z = mean
    z = z * vae.config.scaling_factor
    return z.reshape((b, f) + z.shape[1:])


def decode_video(
    vae: AutoencoderKL, params, latents: jax.Array, *, chunk: int = 4,
    sequential: bool = False,
) -> jax.Array:
    """Scaled latents (B, F, h, w, 4) → video (B, F, 8h, 8w, 3) in [-1, 1],
    decoded ``chunk`` frames at a time (pipeline_tuneavideo.py:243-246).

    ``sequential=True`` runs the chunks through ``lax.map`` — required when
    the decode is traced INTO a larger jitted program: the unrolled chunks
    have no data dependence, so XLA schedules them concurrently and their
    decoder temporaries stack (~1 GB × n_chunks at fp32 512², an OOM on a
    16 GB chip); the scan bounds peak memory to one chunk. Eager callers
    keep the unrolled loop (separate dispatches already serialize it)."""
    b, f = latents.shape[:2]
    z = latents.reshape((b * f,) + latents.shape[2:]) / vae.config.scaling_factor
    n = z.shape[0]
    if sequential and n > chunk:
        # full chunks through lax.map; a non-dividing remainder decodes as
        # one tail call — it may overlap the map, so peak memory is at most
        # TWO chunks' temporaries (vs all of them when fully unrolled)
        full = (n // chunk) * chunk
        zc = z[:full].reshape((full // chunk, chunk) + z.shape[1:])
        img = jax.lax.map(lambda c: vae.apply(params, c, method=vae.decode), zc)
        img = img.reshape((full,) + img.shape[2:])
        if full < n:
            tail = vae.apply(params, z[full:], method=vae.decode)
            img = jnp.concatenate([img, tail], axis=0)
    else:
        outs = []
        for i in range(0, n, chunk):
            outs.append(vae.apply(params, z[i : i + chunk], method=vae.decode))
        img = jnp.concatenate(outs, axis=0)
    return img.reshape((b, f) + img.shape[1:])
