"""The 3-D (video) conditional UNet.

TPU-native re-design of /root/reference/tuneavideo/models/unet.py
(``UNet3DConditionModel``). Same topology as the inflated Stable-Diffusion 1.x
denoiser — 3 cross-attn down blocks + 1 plain down block, cross-attn mid, the
mirrored up path (unet.py:50-64) — expressed as a config-driven linen module
over channels-last (B, F, H, W, C) activations.

The topology is entirely config-driven (block types, widths, per-block
transformer depth and head counts) so larger inflations (e.g. SDXL-shaped
UNets at 1024²) are a config change, not a code change — the stress case
SURVEY §7 calls out.

Weight inflation from 2-D checkpoints (the reference's ``from_pretrained_2d``,
unet.py:417-448) lives in :mod:`videop2p_tpu.models.convert`; the
``'_temp.'``-keys-keep-init rule maps to the temporal attention's
zero-initialized output projection here (models/attention.py).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional, Tuple, Union

import jax
import jax.numpy as jnp
from flax import linen as nn

from videop2p_tpu.models.attention import AttnControl
from videop2p_tpu.models.layers import (
    InflatedConv,
    TimestepEmbedding,
    TpuGroupNorm,
    get_timestep_embedding,
)
from videop2p_tpu.models import unet_blocks
from videop2p_tpu.ops.attention import make_frame_attention_fn

__all__ = ["UNet3DConfig", "UNet3DConditionModel"]


def _per_block(value: Union[int, Tuple[int, ...]], num_blocks: int) -> Tuple[int, ...]:
    if isinstance(value, int):
        return (value,) * num_blocks
    if len(value) != num_blocks:
        raise ValueError(f"per-block value {value} does not match {num_blocks} blocks")
    return tuple(value)


@dataclasses.dataclass(frozen=True)
class UNet3DConfig:
    """Static architecture config (the reference's config-registered kwargs,
    unet.py:42-79). Defaults are the SD-1.x shape."""

    sample_size: int = 64
    in_channels: int = 4
    out_channels: int = 4
    down_block_types: Tuple[str, ...] = (
        "CrossAttnDownBlock3D",
        "CrossAttnDownBlock3D",
        "CrossAttnDownBlock3D",
        "DownBlock3D",
    )
    up_block_types: Tuple[str, ...] = (
        "UpBlock3D",
        "CrossAttnUpBlock3D",
        "CrossAttnUpBlock3D",
        "CrossAttnUpBlock3D",
    )
    block_out_channels: Tuple[int, ...] = (320, 640, 1280, 1280)
    layers_per_block: int = 2
    # int, or per-block tuple (SDXL-style deep upper blocks)
    transformer_depth: Union[int, Tuple[int, ...]] = 1
    attention_head_dim: Union[int, Tuple[int, ...]] = 8  # = num heads (diffusers-0.11 naming)
    cross_attention_dim: int = 768
    norm_num_groups: int = 32
    flip_sin_to_cos: bool = True
    freq_shift: float = 0.0
    gradient_checkpointing: bool = False
    # jax.checkpoint_policies name for remat (None → full recompute inside
    # each block). Measured on v5e at the SD null-text working point:
    # "dots_with_no_batch_dims_saveable" was 2.8× SLOWER (187 s → 521 s) —
    # the saved dot outputs push a 16 GB chip into spills — so full
    # recompute is the default; the knob stays for bigger-HBM parts.
    remat_policy: Optional[str] = None
    # frame-attention kernel: "auto"/"dense" (inference), "chunked"
    # (training: memory-bounded backward), "flash" (Pallas; see ops/attention.py)
    frame_attention: str = "auto"
    # GroupNorm implementation: "auto" = one-pass fused Pallas kernel on TPU
    # at VMEM-fitting sites (ops/groupnorm.py), "xla" = always the two-pass
    # XLA math, "interpret" = kernel in interpret mode (CPU tests). Sharded
    # meshes reach the kernel through the model's group_norm_fn seam
    # (parallel.make_sharded_group_norm_fn) instead of this knob — pjit
    # cannot partition a Pallas custom call, shard_map can
    group_norm: str = "auto"

    @classmethod
    def sd15(cls, **overrides) -> "UNet3DConfig":
        return cls(**overrides)

    @classmethod
    def sdxl(cls, **overrides) -> "UNet3DConfig":
        """SDXL-shaped inflation stress config (BASELINE config 4; SURVEY §7
        hard-part 6): 3 levels, deep upper transformer stacks (depth 2/10),
        64-wide heads, 2048-dim text context, 128² latents (1024² pixels).
        The first level carries no attention (SDXL's DownBlock2D) — its depth
        entry is unused. SDXL's addition embeddings (text_embeds/time_ids
        micro-conditioning) are out of scope: the stress case is the per-block
        topology, which is config-driven here."""
        cfg = dict(
            sample_size=128,
            down_block_types=(
                "DownBlock3D",
                "CrossAttnDownBlock3D",
                "CrossAttnDownBlock3D",
            ),
            up_block_types=(
                "CrossAttnUpBlock3D",
                "CrossAttnUpBlock3D",
                "UpBlock3D",
            ),
            block_out_channels=(320, 640, 1280),
            layers_per_block=2,
            transformer_depth=(1, 2, 10),
            attention_head_dim=(5, 10, 20),  # 64-wide heads per level
            cross_attention_dim=2048,
        )
        cfg.update(overrides)
        return cls(**cfg)

    @classmethod
    def tiny(cls, **overrides) -> "UNet3DConfig":
        """Miniature config for tests: two levels, 8-wide, 2 heads."""
        cfg = dict(
            sample_size=8,
            down_block_types=("CrossAttnDownBlock3D", "DownBlock3D"),
            up_block_types=("UpBlock3D", "CrossAttnUpBlock3D"),
            block_out_channels=(8, 16),
            layers_per_block=1,
            attention_head_dim=2,
            cross_attention_dim=16,
            norm_num_groups=4,
        )
        cfg.update(overrides)
        return cls(**cfg)


class UNet3DConditionModel(nn.Module):
    """Video denoiser ε_θ(x_t, t, text) (reference forward: unet.py:279-415).

    ``__call__(sample, timesteps, encoder_hidden_states, control=None)``:
      * ``sample``: (B, F, H, W, in_channels) latents;
      * ``timesteps``: () or (B,) int;
      * ``encoder_hidden_states``: (B, L, cross_attention_dim) text states, or
        (B, F, L, D) for per-frame embeddings;
      * ``control``: optional :class:`AttnControl` — threads the P2P edit into
        every text-cross / temporal attention site.

    Run with ``mutable=["attn_store"]`` to also collect head-averaged
    attention maps from every controlled site with ≤32² queries (the
    reference's ``AttentionStore``).
    """

    config: UNet3DConfig
    dtype: jnp.dtype = jnp.float32
    frame_attention_fn: Optional[Callable] = None
    # sequence-parallel temporal kernel (e.g. parallel.make_ring_temporal_fn
    # over a frame-sharded mesh); uncontrolled passes only — controlled sites
    # keep dense probabilities for the P2P edit
    temporal_attention_fn: Optional[Callable] = None
    # sharded-mesh GroupNorm seam (parallel.make_sharded_group_norm_fn):
    # carries the fused one-pass kernel onto device meshes via shard_map —
    # sites it does not cover fall back to the two-pass XLA math, never to
    # the naked Pallas path pjit cannot partition
    group_norm_fn: Optional[Callable] = None
    # explicit Megatron row-parallel output projections
    # (parallel.make_megatron_out_dot): replaces the to_out/proj_out
    # matmuls' all-reduce with a psum_scatter over the token axis on
    # tensor-parallel meshes; None → declarative GSPMD (the default)
    row_parallel_dot: Optional[Callable] = None
    # activation fake-quant at the transformer Dense boundaries (w8a8 quant
    # mode — models/quant.py fake_quant_act, wired by ProgramSet/CLIs via
    # clone, same pattern as the seams above); None → byte-identical off path
    act_quant_fn: Optional[Callable] = None

    @nn.compact
    def __call__(
        self,
        sample: jax.Array,
        timesteps: jax.Array,
        encoder_hidden_states: jax.Array,
        control: Optional[AttnControl] = None,
        deep_mode: str = "full",
        deep_feature: Optional[jax.Array] = None,
    ) -> jax.Array:
        """``deep_mode`` (static) is the DeepCache cross-step reuse seam
        (pipelines/reuse.py):

          * ``"full"``    — the whole UNet; returns ``eps`` (unchanged
            contract, byte-identical program — pinned).
          * ``"capture"`` — the whole UNet, additionally returning the deep
            feature: the input to the FINAL up block (the output of up
            block n−2, full spatial resolution). Returns ``(eps, deep)``.
          * ``"shallow"`` — skip every deep stage: conv_in → down block 0
            (no downsample) → the final up block seeded with
            ``deep_feature`` (a previous step's capture) → out convs.
            Adjacent diffusion steps' deep features are nearly identical
            (Ma et al., 2023), so this trades the deep stack's cost for
            one cached activation carried in the sampling scan's state.

        ``capture``/``shallow`` need ≥ 2 resolution levels — the split
        point is the boundary between the last two up blocks.
        """
        cfg = self.config
        n_blocks = len(cfg.block_out_channels)
        if deep_mode not in ("full", "capture", "shallow"):
            raise ValueError(
                f"deep_mode={deep_mode!r} is not 'full', 'capture' or 'shallow'"
            )
        if deep_mode != "full" and n_blocks < 2:
            raise ValueError(
                "deep-feature reuse needs >= 2 resolution levels — "
                f"this config has {n_blocks}"
            )
        if deep_mode == "shallow" and deep_feature is None:
            raise ValueError("deep_mode='shallow' requires deep_feature")
        depths = _per_block(cfg.transformer_depth, n_blocks)
        heads = _per_block(cfg.attention_head_dim, n_blocks)
        frame_attention_fn = (
            self.frame_attention_fn
            if self.frame_attention_fn is not None
            else make_frame_attention_fn(cfg.frame_attention)
        )

        # --- time embedding (unet.py:324-346) ---
        timesteps = jnp.asarray(timesteps)
        if timesteps.ndim == 0:
            timesteps = jnp.broadcast_to(timesteps, (sample.shape[0],))
        temb = get_timestep_embedding(
            timesteps,
            cfg.block_out_channels[0],
            flip_sin_to_cos=cfg.flip_sin_to_cos,
            downscale_freq_shift=cfg.freq_shift,
        ).astype(self.dtype)
        temb = TimestepEmbedding(
            cfg.block_out_channels[0] * 4, dtype=self.dtype, name="time_embedding"
        )(temb)

        # --- down path (unet.py:359-374) ---
        x = InflatedConv(cfg.block_out_channels[0], dtype=self.dtype, name="conv_in")(sample)
        res_stack = [x]
        down_types = (cfg.down_block_types[:1] if deep_mode == "shallow"
                      else cfg.down_block_types)
        for i, block_type in enumerate(down_types):
            is_final = i == n_blocks - 1
            block = unet_blocks.get_down_block(
                block_type,
                remat=cfg.gradient_checkpointing,
                remat_policy=cfg.remat_policy,
                out_channels=cfg.block_out_channels[i],
                num_layers=cfg.layers_per_block,
                transformer_depth=depths[i],
                attn_heads=heads[i],
                # the shallow path never descends: the downsample conv's
                # output only feeds the deep stages being skipped, so the
                # block is built without it (params bind by name — the
                # unvisited downsample kernel is simply not looked up)
                add_downsample=not is_final and deep_mode != "shallow",
                norm_groups=cfg.norm_num_groups,
                gn_impl=cfg.group_norm,
                group_norm_fn=self.group_norm_fn,
                dtype=self.dtype,
                frame_attention_fn=frame_attention_fn,
                temporal_attention_fn=self.temporal_attention_fn,
                row_parallel_dot=self.row_parallel_dot,
                act_quant_fn=self.act_quant_fn,
                name=f"down_blocks_{i}",
            )
            if block_type == "CrossAttnDownBlock3D":
                x, res = block(x, temb, encoder_hidden_states, control)
            else:
                x, res = block(x, temb)
            res_stack.extend(res)

        if deep_mode != "shallow":
            # --- mid (unet.py:377) ---
            mid_cls = (
                nn.remat(
                    unet_blocks.UNetMidBlock3DCrossAttn,
                    policy=unet_blocks.resolve_remat_policy(cfg.remat_policy),
                )
                if cfg.gradient_checkpointing
                else unet_blocks.UNetMidBlock3DCrossAttn
            )
            x = mid_cls(
                channels=cfg.block_out_channels[-1],
                transformer_depth=depths[-1],
                attn_heads=heads[-1],
                norm_groups=cfg.norm_num_groups,
                gn_impl=cfg.group_norm,
                group_norm_fn=self.group_norm_fn,
                dtype=self.dtype,
                frame_attention_fn=frame_attention_fn,
                temporal_attention_fn=self.temporal_attention_fn,
                row_parallel_dot=self.row_parallel_dot,
                act_quant_fn=self.act_quant_fn,
                name="mid_block",
            )(x, temb, encoder_hidden_states, control)

        # --- up path (unet.py:382-405) ---
        rev_channels = tuple(reversed(cfg.block_out_channels))
        rev_heads = tuple(reversed(heads))
        rev_depths = tuple(reversed(depths))
        deep = None
        up_indices = ([n_blocks - 1] if deep_mode == "shallow"
                      else range(len(cfg.up_block_types)))
        if deep_mode == "shallow":
            # seed the final up block with the cached deep feature; the
            # skip connections it concatenates ([conv_in, down block 0's
            # resnet outputs]) were just recomputed above
            x = deep_feature.astype(self.dtype)
        for i in up_indices:
            block_type = cfg.up_block_types[i]
            is_final = i == n_blocks - 1
            num_layers = cfg.layers_per_block + 1
            res = tuple(res_stack[-num_layers:])
            del res_stack[-num_layers:]
            if is_final and deep_mode == "capture":
                # the DeepCache split point: everything above this input
                # (deep down blocks, mid, up blocks 0..n−2) is what a
                # shallow step skips
                deep = x
            block = unet_blocks.get_up_block(
                block_type,
                remat=cfg.gradient_checkpointing,
                remat_policy=cfg.remat_policy,
                out_channels=rev_channels[i],
                num_layers=num_layers,
                transformer_depth=rev_depths[i],
                attn_heads=rev_heads[i],
                add_upsample=not is_final,
                norm_groups=cfg.norm_num_groups,
                gn_impl=cfg.group_norm,
                group_norm_fn=self.group_norm_fn,
                dtype=self.dtype,
                frame_attention_fn=frame_attention_fn,
                temporal_attention_fn=self.temporal_attention_fn,
                row_parallel_dot=self.row_parallel_dot,
                act_quant_fn=self.act_quant_fn,
                name=f"up_blocks_{i}",
            )
            if block_type == "CrossAttnUpBlock3D":
                x = block(x, res, temb, encoder_hidden_states, control)
            else:
                x = block(x, res, temb)

        # --- out (unet.py:407-409) ---
        x = TpuGroupNorm(
            num_groups=cfg.norm_num_groups, epsilon=1e-5, dtype=self.dtype,
            act="silu", impl=cfg.group_norm,
            group_norm_fn=self.group_norm_fn, name="conv_norm_out",
        )(x)
        x = InflatedConv(cfg.out_channels, dtype=self.dtype, name="conv_out")(x)
        if deep_mode == "capture":
            return x, deep
        return x
