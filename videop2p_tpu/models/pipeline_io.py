"""Diffusers-layout pipeline directory I/O — the Stage-1 → Stage-2 contract.

The reference's two stages communicate via the filesystem: Stage 1 ends with
``pipeline.save_pretrained(output_dir)`` (/root/reference/run_tuning.py:387-393)
and Stage 2 loads that directory as ``pretrained_model_path``
(run_videop2p.py:101-114). This module reads and writes the same layout::

    <dir>/
      model_index.json
      unet/   config.json + diffusion_pytorch_model.safetensors
      vae/    config.json + diffusion_pytorch_model.safetensors
      text_encoder/ config.json + model.safetensors
      tokenizer/    (CLIP BPE files — copied through, never rewritten)
      scheduler/    scheduler_config.json

so a checkpoint produced by the reference (or any diffusers SD-1.x dump)
loads here, and a Stage-1 checkpoint written here loads in the reference.
Weights cross the boundary through :mod:`videop2p_tpu.models.convert`.
"""

from __future__ import annotations

import json
import os
import shutil
from dataclasses import dataclass
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from videop2p_tpu.models import convert
from videop2p_tpu.models.clip import CLIPTextConfig, CLIPTextEncoder
from videop2p_tpu.models.unet import UNet3DConditionModel, UNet3DConfig
from videop2p_tpu.models.vae import AutoencoderKL, VAEConfig

__all__ = ["LoadedPipeline", "load_pipeline", "save_pipeline"]

_WEIGHT_NAMES = (
    "diffusion_pytorch_model.safetensors",
    "diffusion_pytorch_model.bin",
    "model.safetensors",
    "pytorch_model.bin",
)


def _find_weights(subdir: str) -> Optional[str]:
    for name in _WEIGHT_NAMES:
        p = os.path.join(subdir, name)
        if os.path.exists(p):
            return p
    return None


def _read_json(path: str) -> Dict[str, Any]:
    with open(path) as f:
        return json.load(f)


@dataclass
class LoadedPipeline:
    unet: UNet3DConditionModel
    unet_params: Dict
    vae: Optional[AutoencoderKL]
    vae_params: Optional[Dict]
    text_encoder: Optional[CLIPTextEncoder]
    text_params: Optional[Dict]
    tokenizer_dir: Optional[str]
    scheduler_config: Dict[str, Any]
    inflation_report: Dict[str, list]


def _unet_config_from_diffusers(cfg: Dict[str, Any], **overrides) -> UNet3DConfig:
    """Map a diffusers UNet2D/3D config.json to :class:`UNet3DConfig`
    (the reference rewrites 2-D block types to 3-D the same way,
    unet.py:427-438)."""
    def threed(name: str) -> str:
        return name.replace("2D", "3D")

    kw = dict(
        sample_size=cfg.get("sample_size", 64),
        in_channels=cfg.get("in_channels", 4),
        out_channels=cfg.get("out_channels", 4),
        down_block_types=tuple(threed(b) for b in cfg["down_block_types"]),
        up_block_types=tuple(threed(b) for b in cfg["up_block_types"]),
        block_out_channels=tuple(cfg["block_out_channels"]),
        layers_per_block=cfg.get("layers_per_block", 2),
        attention_head_dim=(
            tuple(a) if isinstance(cfg.get("attention_head_dim", 8), (list, tuple))
            else cfg.get("attention_head_dim", 8)
        ),
        cross_attention_dim=cfg.get("cross_attention_dim", 768),
        norm_num_groups=cfg.get("norm_num_groups", 32),
        flip_sin_to_cos=cfg.get("flip_sin_to_cos", True),
        freq_shift=cfg.get("freq_shift", 0),
    )
    kw.update(overrides)
    return UNet3DConfig(**kw)


def load_pipeline(
    path: str,
    *,
    dtype: jnp.dtype = jnp.float32,
    load_vae: bool = True,
    load_text_encoder: bool = True,
    init_key: Optional[jax.Array] = None,
    **unet_overrides,
) -> LoadedPipeline:
    """Load a diffusers-layout SD/Tune-A-Video checkpoint directory into flax
    models + params (2-D checkpoints inflate; tuned 3-D ones load fully)."""
    if init_key is None:
        init_key = jax.random.key(0)

    unet_dir = os.path.join(path, "unet")
    unet_cfg = _unet_config_from_diffusers(
        _read_json(os.path.join(unet_dir, "config.json")), **unet_overrides
    )
    unet = UNet3DConditionModel(config=unet_cfg, dtype=dtype)
    sample = jnp.zeros((1, 2, unet_cfg.sample_size, unet_cfg.sample_size, unet_cfg.in_channels))
    text = jnp.zeros((1, 77, unet_cfg.cross_attention_dim))
    abstract = jax.eval_shape(
        lambda: unet.init(init_key, sample, jnp.asarray(0), text)
    )["params"]
    # materialize inits only for params the checkpoint may not carry
    init_params = jax.jit(unet.init)(init_key, sample, jnp.asarray(0), text)["params"]
    sd = convert.load_state_dict(_find_weights(unet_dir))
    unet_params, report = convert.unet3d_params_from_torch(sd, init_params)

    vae = vae_params = None
    vae_dir = os.path.join(path, "vae")
    if load_vae and os.path.isdir(vae_dir):
        vcfg_raw = _read_json(os.path.join(vae_dir, "config.json"))
        vcfg = VAEConfig(
            in_channels=vcfg_raw.get("in_channels", 3),
            out_channels=vcfg_raw.get("out_channels", 3),
            latent_channels=vcfg_raw.get("latent_channels", 4),
            block_out_channels=tuple(vcfg_raw.get("block_out_channels", (128, 256, 512, 512))),
            layers_per_block=vcfg_raw.get("layers_per_block", 2),
            norm_num_groups=vcfg_raw.get("norm_num_groups", 32),
            scaling_factor=vcfg_raw.get("scaling_factor", 0.18215),
        )
        vae = AutoencoderKL(config=vcfg, dtype=dtype)
        probe = jnp.zeros((1, 32, 32, vcfg.in_channels))
        v_init = jax.jit(vae.init)(init_key, probe, init_key)["params"]
        v_sd = convert.load_state_dict(_find_weights(vae_dir))
        vae_params = {"params": convert.vae_params_from_torch(v_sd, v_init)}

    text_encoder = text_params = None
    te_dir = os.path.join(path, "text_encoder")
    if load_text_encoder and os.path.isdir(te_dir):
        tcfg_raw = _read_json(os.path.join(te_dir, "config.json"))
        tcfg = CLIPTextConfig(
            vocab_size=tcfg_raw.get("vocab_size", 49408),
            hidden_size=tcfg_raw.get("hidden_size", 768),
            intermediate_size=tcfg_raw.get("intermediate_size", 3072),
            num_hidden_layers=tcfg_raw.get("num_hidden_layers", 12),
            num_attention_heads=tcfg_raw.get("num_attention_heads", 12),
            max_position_embeddings=tcfg_raw.get("max_position_embeddings", 77),
        )
        text_encoder = CLIPTextEncoder(config=tcfg, dtype=dtype)
        t_init = jax.jit(text_encoder.init)(
            init_key, jnp.zeros((1, 8), jnp.int32)
        )["params"]
        t_sd = convert.load_state_dict(_find_weights(te_dir))
        text_params = {"params": convert.clip_params_from_torch(t_sd, t_init)}

    tok_dir = os.path.join(path, "tokenizer")
    sched_cfg_path = os.path.join(path, "scheduler", "scheduler_config.json")
    return LoadedPipeline(
        unet=unet,
        unet_params={"params": unet_params},
        vae=vae,
        vae_params=vae_params,
        text_encoder=text_encoder,
        text_params=text_params,
        tokenizer_dir=tok_dir if os.path.isdir(tok_dir) else None,
        scheduler_config=_read_json(sched_cfg_path) if os.path.exists(sched_cfg_path) else {},
        inflation_report=report,
    )


def save_pipeline(
    path: str,
    unet_config: UNet3DConfig,
    unet_params: Dict,
    *,
    source_dir: Optional[str] = None,
    scheduler_config: Optional[Dict[str, Any]] = None,
) -> None:
    """Write a diffusers-layout pipeline dir (run_tuning.py:387-393).

    The tuned UNet is exported through the torch-layout name map; frozen
    components (vae / text_encoder / tokenizer / scheduler) are copied
    through from ``source_dir`` when given, since tuning never touches them.
    """
    from safetensors.numpy import save_file

    os.makedirs(path, exist_ok=True)
    unet_dir = os.path.join(path, "unet")
    os.makedirs(unet_dir, exist_ok=True)
    params = unet_params.get("params", unet_params)
    sd = convert.unet3d_params_to_torch(params)
    save_file({k: np.ascontiguousarray(v) for k, v in sd.items()},
              os.path.join(unet_dir, "diffusion_pytorch_model.safetensors"))
    cfg = unet_config
    with open(os.path.join(unet_dir, "config.json"), "w") as f:
        json.dump(
            {
                "_class_name": "UNet3DConditionModel",
                "sample_size": cfg.sample_size,
                "in_channels": cfg.in_channels,
                "out_channels": cfg.out_channels,
                "down_block_types": list(cfg.down_block_types),
                "up_block_types": list(cfg.up_block_types),
                "block_out_channels": list(cfg.block_out_channels),
                "layers_per_block": cfg.layers_per_block,
                "attention_head_dim": (
                    list(cfg.attention_head_dim)
                    if isinstance(cfg.attention_head_dim, tuple)
                    else cfg.attention_head_dim
                ),
                "cross_attention_dim": cfg.cross_attention_dim,
                "norm_num_groups": cfg.norm_num_groups,
                "flip_sin_to_cos": cfg.flip_sin_to_cos,
                "freq_shift": cfg.freq_shift,
            },
            f,
            indent=2,
        )
    if scheduler_config:
        sdir = os.path.join(path, "scheduler")
        os.makedirs(sdir, exist_ok=True)
        with open(os.path.join(sdir, "scheduler_config.json"), "w") as f:
            json.dump(scheduler_config, f, indent=2)
    if source_dir:
        for sub in ("vae", "text_encoder", "tokenizer", "scheduler"):
            src = os.path.join(source_dir, sub)
            dst = os.path.join(path, sub)
            if os.path.isdir(src) and not os.path.isdir(dst):
                shutil.copytree(src, dst)
    index = {
        "_class_name": "TuneAVideoPipeline",
        "unet": ["videop2p_tpu", "UNet3DConditionModel"],
        "vae": ["diffusers", "AutoencoderKL"],
        "text_encoder": ["transformers", "CLIPTextModel"],
        "tokenizer": ["transformers", "CLIPTokenizer"],
        "scheduler": ["diffusers", "DDIMScheduler"],
    }
    with open(os.path.join(path, "model_index.json"), "w") as f:
        json.dump(index, f, indent=2)
