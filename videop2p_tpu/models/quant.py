"""Post-training UNet weight quantization (ISSUE 15, Q-Diffusion-style).

int8 (or float8-e4m3 where the dtype exists) weight storage with
PER-OUTPUT-CHANNEL symmetric scales, computed once at load time
(:func:`videop2p_tpu.models.convert.quantize_unet_params`). The storage
convention follows the float8 temporal-map capture
(``pipelines/fast.py choose_cached_maps``): store low-precision, upcast
to the sibling compute dtype exactly at the matmul seam —
:func:`videop2p_tpu.pipelines.sampling.make_unet_fn` dequantizes INSIDE
the traced program, so XLA holds the 1-byte weights as program inputs
(≈4× parameter bytes-accessed cut vs fp32; the dequant itself is a fused
elementwise multiply) and every matmul still runs in the model dtype.

Modes (``QUANT_MODES``):
  * ``"off"``  — no quantization; the program is byte-identical (pinned).
  * ``"w8"``   — int8 weights, per-output-channel scales.
  * ``"w8a8"`` — w8 plus dynamic per-tensor activation fake-quant at the
    Dense boundaries of models/attention.py (``fake_quant_act`` wired via
    the model's ``act_quant_fn`` seam, same threading as
    ``row_parallel_dot``).

First/last-layer precision practice (Q-Diffusion §4): ``conv_in``,
``conv_out`` and the time embedding stay full precision — ``SKIP_MODULES``.

Stdlib + jax only — safe for the import-guarded packages to reach.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

__all__ = [
    "QUANT_MODES",
    "SKIP_MODULES",
    "QuantizedTensor",
    "validate_quant_mode",
    "quantize_weight",
    "fake_quant_act",
    "quantize_tree",
    "has_quantized",
    "dequantize_tree",
    "quant_weight_dtype",
]

QUANT_MODES = ("off", "w8", "w8a8")

# full-precision islands: the in/out convs and the time MLP carry the
# widest dynamic range for the fewest parameters (Q-Diffusion keeps the
# first and last layers unquantized for the same reason)
SKIP_MODULES = ("conv_in", "conv_out", "time_embedding")


def validate_quant_mode(mode: Optional[str]) -> str:
    """Normalize/validate a ``quant_mode`` knob value (None → "off")."""
    mode = "off" if mode is None else str(mode)
    if mode not in QUANT_MODES:
        raise ValueError(
            f"quant_mode={mode!r} is not one of {QUANT_MODES} — "
            "off: full precision (bit-exact); w8: int8 weights with "
            "per-output-channel scales; w8a8: w8 plus dynamic per-tensor "
            "activation fake-quant at the attention Dense boundaries"
        )
    return mode


def quant_weight_dtype(name: str = "int8"):
    """Resolve a storage dtype name, preferring int8; ``"fp8"`` selects
    float8-e4m3 where this jax exposes it (falls back to int8 otherwise —
    same graceful degradation as ``choose_cached_maps``)."""
    if name in ("fp8", "float8_e4m3fn"):
        dt = getattr(jnp, "float8_e4m3fn", None)
        if dt is not None:
            return dt
    return jnp.int8


@jax.tree_util.register_pytree_node_class
class QuantizedTensor:
    """A low-precision weight: ``qvalue`` (int8 or fp8-e4m3, the original
    kernel's shape) plus a broadcastable fp32 per-output-channel ``scale``
    (flax kernels put the output channel LAST — Dense ``(in, out)``,
    InflatedConv ``(kh, kw, in, out)`` — so the scale reduces every axis
    but the last). ``dequantize`` is the one seam back to compute dtype."""

    def __init__(self, qvalue: jax.Array, scale: jax.Array):
        self.qvalue = qvalue
        self.scale = scale

    # array-protocol conveniences so shape/byte accounting (tree_bytes,
    # eval_shape prints) keep working over quantized trees
    @property
    def shape(self) -> Tuple[int, ...]:
        return self.qvalue.shape

    @property
    def ndim(self) -> int:
        return self.qvalue.ndim

    @property
    def dtype(self):
        return self.qvalue.dtype

    def dequantize(self, dtype=jnp.float32) -> jax.Array:
        return (self.qvalue.astype(jnp.float32) * self.scale).astype(dtype)

    def tree_flatten(self):
        return (self.qvalue, self.scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        del aux
        return cls(*children)

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return (f"QuantizedTensor(shape={tuple(self.qvalue.shape)}, "
                f"dtype={jnp.dtype(self.qvalue.dtype).name})")


def quantize_weight(w: jax.Array, *, dtype=jnp.int8) -> QuantizedTensor:
    """One kernel → :class:`QuantizedTensor` with symmetric
    per-output-channel scales (absmax over every axis but the last)."""
    wf = jnp.asarray(w).astype(jnp.float32)
    axes = tuple(range(wf.ndim - 1))
    amax = jnp.max(jnp.abs(wf), axis=axes, keepdims=True)
    if jnp.issubdtype(jnp.dtype(dtype), jnp.integer):
        qmax = float(jnp.iinfo(dtype).max)  # 127 — symmetric, no -128
        scale = jnp.maximum(amax, 1e-12) / qmax
        q = jnp.clip(jnp.round(wf / scale), -qmax, qmax).astype(dtype)
    else:
        qmax = float(jnp.finfo(dtype).max)  # 448 for e4m3
        scale = jnp.maximum(amax, 1e-12) / qmax
        q = (wf / scale).astype(dtype)
    return QuantizedTensor(q, scale)


def fake_quant_act(x: jax.Array) -> jax.Array:
    """Dynamic per-tensor symmetric int8 round-trip for activations
    (the ``w8a8`` mode's ``act_quant_fn``): quantize-dequantize in fp32,
    return in the input dtype — same program structure, a8 noise model."""
    if not jnp.issubdtype(x.dtype, jnp.floating):
        return x
    xf = x.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(xf)), 1e-8) / 127.0
    q = jnp.clip(jnp.round(xf / scale), -127.0, 127.0)
    return (q * scale).astype(x.dtype)


def _path_names(path) -> Tuple[str, ...]:
    names = []
    for k in path:
        name = getattr(k, "key", None)
        if name is None:
            name = getattr(k, "name", None)
        if name is None:
            name = str(k)
        names.append(str(name))
    return tuple(names)


def quantize_tree(params: Any, *, dtype=jnp.int8,
                  skip: Tuple[str, ...] = SKIP_MODULES) -> Any:
    """Quantize every matmul kernel in a flax param tree: leaves whose
    path ends in ``"kernel"`` with ndim ≥ 2, outside the ``skip`` modules.
    Biases, norms and embeddings stay full precision (they are a rounding
    error of the byte budget and carry the quality-sensitive offsets)."""

    def maybe(path, leaf):
        names = _path_names(path)
        if (names and names[-1] == "kernel"
                and getattr(leaf, "ndim", 0) >= 2
                and not any(s in names for s in skip)):
            return quantize_weight(leaf, dtype=dtype)
        return leaf

    return jax.tree_util.tree_map_with_path(maybe, params)


def has_quantized(params: Any) -> bool:
    """True when any leaf of ``params`` sits under a
    :class:`QuantizedTensor` node."""
    leaves = jax.tree_util.tree_leaves(
        params, is_leaf=lambda x: isinstance(x, QuantizedTensor)
    )
    return any(isinstance(x, QuantizedTensor) for x in leaves)


def dequantize_tree(params: Any, dtype=jnp.float32) -> Any:
    """Upcast every :class:`QuantizedTensor` back to ``dtype`` (the
    sibling-compute-dtype seam ``make_unet_fn`` runs inside the traced
    program); non-quantized leaves pass through untouched."""
    return jax.tree_util.tree_map(
        lambda x: x.dequantize(dtype) if isinstance(x, QuantizedTensor) else x,
        params,
        is_leaf=lambda x: isinstance(x, QuantizedTensor),
    )
