"""Weight interop: diffusers/transformers torch checkpoints ↔ flax params.

Three jobs (SURVEY §7 step 3 and §5.4):

  * **2D→3D inflation** (``unet3d_params_from_torch``) — load a Stable
    Diffusion UNet2DConditionModel state dict into the video UNet. Parameters
    with no 2-D counterpart (``attn_temp``/``norm_temp``) keep their fresh
    init — the reference's ``'_temp.'``-keys rule
    (/root/reference/tuneavideo/models/unet.py:446-448); the zero-initialized
    temporal output projection then makes inflation an identity.
    A *tuned* 3-D checkpoint (which does contain ``attn_temp`` keys, as saved
    by Stage 1) loads through the same path.
  * **export** (``unet3d_params_to_torch``) — the inverse mapping, producing
    a reference-compatible (Tune-A-Video layout) state dict so Stage-1 output
    remains consumable by the original codebase (the Stage-1→Stage-2 contract,
    run_tuning.py:387-393).
  * **VAE / CLIP import** (``vae_params_from_torch``,
    ``clip_params_from_torch``) — diffusers ``AutoencoderKL`` and transformers
    ``CLIPTextModel`` state dicts into the flax implementations; CLIP import
    is validated numerically against the torch model in tests/test_convert.py.

All functions take a plain ``{name: numpy array}`` dict — use
``load_state_dict`` for ``.safetensors``/``.bin`` files — so torch is only
touched at the file boundary.
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Tuple

import jax
import numpy as np
from flax import traverse_util

__all__ = [
    "load_state_dict",
    "unet3d_params_from_torch",
    "unet3d_params_to_torch",
    "quantize_unet_params",
    "vae_params_from_torch",
    "clip_params_from_torch",
]

Array = np.ndarray
StateDict = Dict[str, Array]


def load_state_dict(path: str) -> StateDict:
    """Read a ``.safetensors`` or torch ``.bin`` file into numpy arrays."""
    if path.endswith(".safetensors"):
        from safetensors.numpy import load_file

        return dict(load_file(path))
    import torch

    sd = torch.load(path, map_location="cpu", weights_only=True)
    return {k: v.numpy() for k, v in sd.items()}


# --------------------------------------------------------------------- #
# flax-path → torch-key translation (UNet)
# --------------------------------------------------------------------- #

_SEG_MAP = {
    "downsample": "downsamplers.0",
    "upsample": "upsamplers.0",
    "proj_geglu": "net.0.proj",
}
_INDEXED = ("down_blocks", "up_blocks", "attentions", "resnets", "blocks_")


def _flax_path_to_torch(path: Tuple[str, ...]) -> Tuple[str, str]:
    """(torch key prefix, kind) for one flax param path (sans leaf).

    kind ∈ {"conv", "dense", "norm", "raw"} drives the tensor transform.
    """
    segs = []
    kind = "raw"
    toks = list(path)
    leaf = toks[-1]
    body = toks[:-1]
    # InflatedConv wraps an nn.Conv named "conv": drop that segment; only the
    # kernel needs the conv layout transform (biases are 1-D pass-through)
    if body and body[-1] == "conv":
        body = body[:-1]
        if leaf == "kernel":
            kind = "conv"
    for t in body:
        if t.startswith("blocks_"):
            segs.append(f"transformer_blocks.{t.split('_')[1]}")
        elif (
            t.startswith("down_blocks_")
            or t.startswith("up_blocks_")
            or t.startswith("attentions_")
            or t.startswith("resnets_")
            or t.startswith("layers_")
        ):
            base, i = t.rsplit("_", 1)
            segs.append(f"{base}.{i}")
        elif t in _SEG_MAP:
            segs.append(_SEG_MAP[t])
        elif t == "proj_out" and segs and segs[-1] == "ff":
            segs.append("net.2")
        elif t == "to_out":
            segs.append("to_out.0")
        else:
            segs.append(t)
    key = ".".join(segs)
    if kind != "conv":
        if leaf == "kernel":
            kind = "dense"
        elif leaf == "scale":
            kind = "norm"
        elif leaf == "embedding":
            kind = "raw"
    torch_leaf = {"kernel": "weight", "scale": "weight", "bias": "bias", "embedding": "weight"}[
        leaf
    ]
    return f"{key}.{torch_leaf}", kind


def _to_flax_tensor(t: Array, kind: str, target_shape: Tuple[int, ...]) -> Array:
    if kind == "conv":
        if t.ndim == 4:
            return np.transpose(t, (2, 3, 1, 0))
        raise ValueError(f"expected 4-D conv weight, got {t.shape}")
    if kind == "dense":
        if t.ndim == 4 and t.shape[2] == t.shape[3] == 1:
            # 1×1 conv in torch ↔ Dense in channels-last flax
            t = t[:, :, 0, 0]
        if t.ndim == 2:
            return np.transpose(t)
        raise ValueError(f"expected 2-D linear weight, got {t.shape}")
    return t


def _from_flax_tensor(t: Array, kind: str, conv1x1: bool = False) -> Array:
    if kind == "conv":
        return np.transpose(t, (3, 2, 0, 1))
    if kind == "dense":
        w = np.transpose(t)
        if conv1x1:
            w = w[:, :, None, None]
        return w
    return t


def unet3d_params_from_torch(
    state_dict: StateDict,
    abstract_params,
    *,
    strict_missing: bool = False,
) -> Tuple[Dict, Dict[str, list]]:
    """Map a diffusers UNet2D (or saved Tune-A-Video UNet3D) state dict onto
    the video UNet's param tree.

    ``abstract_params``: the target "params" tree (real or ShapeDtypeStruct
    leaves) defining structure and shapes. Returns ``(params, report)`` where
    report lists ``kept_init`` (our params with no torch key — must be
    temporal-only unless ``strict_missing``) and ``unused`` torch keys.
    """
    flat = traverse_util.flatten_dict(abstract_params)
    out = {}
    kept_init, used = [], set()
    for path, leaf in flat.items():
        torch_key, kind = _flax_path_to_torch(path)
        src = state_dict.get(torch_key)
        if src is None and kind == "dense":
            # proj_in/proj_out may be stored as 1×1 convs (SD1.x) — same key,
            # handled by _to_flax_tensor; nothing else to try
            pass
        if src is None:
            path_str = "/".join(path)
            if not strict_missing and ("attn_temp" in path_str or "norm_temp" in path_str):
                # 2D checkpoint: temporal params keep their fresh init
                # (unet.py:446-448)
                out[path] = np.asarray(leaf) if hasattr(leaf, "__array__") else leaf
                kept_init.append(path_str)
                continue
            raise KeyError(
                f"no torch key {torch_key!r} for param {path_str!r} "
                f"(and it is not a temporal-inflation param)"
            )
        arr = _to_flax_tensor(np.asarray(src), kind, getattr(leaf, "shape", None))
        if tuple(arr.shape) != tuple(leaf.shape):
            raise ValueError(
                f"shape mismatch for {torch_key!r}: torch {arr.shape} vs "
                f"flax {tuple(leaf.shape)}"
            )
        out[path] = arr.astype(np.asarray(leaf).dtype if hasattr(leaf, "__array__") else leaf.dtype)
        used.add(torch_key)
    unused = [k for k in state_dict if k not in used]
    return traverse_util.unflatten_dict(out), {"kept_init": kept_init, "unused": unused}


def unet3d_params_to_torch(params) -> StateDict:
    """Inverse mapping: flax video-UNet params → Tune-A-Video-layout state
    dict (numpy). ``proj_in``/``proj_out`` of the transformer are written as
    1×1 convs, matching the reference module (attention.py:74-88)."""
    flat = traverse_util.flatten_dict(params)
    out: StateDict = {}
    for path, leaf in flat.items():
        torch_key, kind = _flax_path_to_torch(path)
        conv1x1 = kind == "dense" and path[-1] == "kernel" and (
            path[-2] in ("proj_in", "proj_out") and "blocks_0" not in path
        )
        out[torch_key] = _from_flax_tensor(np.asarray(leaf), kind, conv1x1=conv1x1)
    return out


def quantize_unet_params(params, mode: str = "w8", weight_dtype: str = "int8"):
    """Post-training quantization of a flax video-UNet param tree at load
    time (ISSUE 15): every matmul kernel outside the first/last-layer
    precision islands becomes a :class:`~videop2p_tpu.models.quant
    .QuantizedTensor` (int8 or fp8-e4m3 storage + per-output-channel fp32
    scales). The low-precision tree feeds the SAME ``make_unet_fn``
    programs — the adapter dequantizes inside the trace, so the 1-byte
    weights stay the program inputs. ``mode="off"`` returns ``params``
    unchanged (the pinned bit-exact path); ``w8`` and ``w8a8`` quantize
    identically here (the a8 half is the model's ``act_quant_fn`` seam,
    wired by the caller). Works on either the bare ``{"params": ...}``
    collection dict or its inner tree.
    """
    from videop2p_tpu.models.quant import quantize_tree, quant_weight_dtype, \
        validate_quant_mode

    mode = validate_quant_mode(mode)
    if mode == "off":
        return params
    dtype = quant_weight_dtype(weight_dtype)
    if isinstance(params, dict) and "params" in params:
        out = dict(params)
        out["params"] = quantize_tree(params["params"], dtype=dtype)
        return out
    return quantize_tree(params, dtype=dtype)


# --------------------------------------------------------------------- #
# VAE
# --------------------------------------------------------------------- #

_VAE_ATTN_ALIASES = {
    # diffusers ≥0.15 name : 0.11-era name
    "to_q": "query",
    "to_k": "key",
    "to_v": "value",
    "to_out.0": "proj_attn",
}


def _vae_flax_to_torch(path: Tuple[str, ...]) -> Tuple[str, str]:
    toks = list(path)
    leaf = toks.pop()
    segs = []
    for t in toks:
        if t.startswith("down_") and t.split("_")[1].isdigit():
            parts = t.split("_")  # down_{i}_resnets_{j} | down_{i}_downsample
            if parts[2] == "downsample":
                segs.append(f"down_blocks.{parts[1]}.downsamplers.0.conv")
            else:
                segs.append(f"down_blocks.{parts[1]}.{parts[2]}.{parts[3]}")
        elif t.startswith("up_") and t.split("_")[1].isdigit():
            parts = t.split("_")  # up_{i}_resnets_{j} | up_{i}_upsample
            if parts[2] == "upsample":
                segs.append(f"up_blocks.{parts[1]}.upsamplers.0.conv")
            else:
                segs.append(f"up_blocks.{parts[1]}.{parts[2]}.{parts[3]}")
        elif t.startswith("mid_resnets_"):
            segs.append(f"mid_block.resnets.{t.rsplit('_', 1)[1]}")
        elif t == "mid_attn":
            segs.append("mid_block.attentions.0")
        elif t == "to_out":
            segs.append("to_out.0")
        else:
            segs.append(t)
    kind = "norm" if leaf == "scale" else ("dense" if leaf == "kernel" else "raw")
    torch_leaf = {"kernel": "weight", "scale": "weight", "bias": "bias"}[leaf]
    return ".".join(segs) + "." + torch_leaf, kind


def vae_params_from_torch(state_dict: StateDict, abstract_params) -> Dict:
    """diffusers AutoencoderKL state dict → flax params. Handles both
    downsample naming eras and both attention naming eras."""
    flat = traverse_util.flatten_dict(abstract_params)
    out = {}
    for path, leaf in flat.items():
        torch_key, kind = _vae_flax_to_torch(path)
        # our conv modules are plain nn.Conv (kernel 4-D): fix the kind
        if kind == "dense" and len(getattr(leaf, "shape", ())) == 4:
            kind = "conv"
        cands = [torch_key]
        if "downsample" in torch_key:
            cands.append(torch_key.replace("downsample.", "downsamplers.0.conv."))
        if "_downsample" in torch_key:  # encoder down_{i}_downsample
            pass
        for new, old in _VAE_ATTN_ALIASES.items():
            if f".{new}." in torch_key:
                cands.append(torch_key.replace(f".{new}.", f".{old}."))
        src = next((state_dict[c] for c in cands if c in state_dict), None)
        if src is None:
            raise KeyError(f"no torch key for VAE param {'/'.join(path)} (tried {cands})")
        arr = np.asarray(src)
        if kind == "dense" and arr.ndim == 2 and len(leaf.shape) == 2:
            arr = np.transpose(arr)
        elif arr.ndim == 4:
            arr = np.transpose(arr, (2, 3, 1, 0))
        elif kind == "dense" and arr.ndim == 4:
            arr = np.transpose(arr[:, :, 0, 0])
        if tuple(arr.shape) != tuple(leaf.shape):
            raise ValueError(f"VAE shape mismatch at {torch_key}: {arr.shape} vs {leaf.shape}")
        out[path] = arr
    return traverse_util.unflatten_dict(out)


# --------------------------------------------------------------------- #
# CLIP text encoder
# --------------------------------------------------------------------- #


def clip_params_from_torch(state_dict: StateDict, abstract_params) -> Dict:
    """transformers CLIPTextModel state dict → flax CLIPTextEncoder params."""
    pre = "text_model."
    sd = {
        (k[len(pre):] if k.startswith(pre) else k): np.asarray(v)
        for k, v in state_dict.items()
    }
    flat = traverse_util.flatten_dict(abstract_params)
    out = {}
    for path, leaf in flat.items():
        toks = list(path)
        leaf_name = toks.pop()
        if toks == ["token_embedding"] and leaf_name == "embedding":
            arr = sd["embeddings.token_embedding.weight"]
        elif not toks and leaf_name == "position_embedding":
            arr = sd["embeddings.position_embedding.weight"]
        elif toks and toks[0] == "final_layer_norm":
            arr = sd[f"final_layer_norm.{'weight' if leaf_name == 'scale' else 'bias'}"]
        else:
            # layers_{i}/(self_attn|layer_norm1|layer_norm2|fc1|fc2)/...
            i = toks[0].rsplit("_", 1)[1]
            rest = toks[1:]
            if rest and rest[0] in ("fc1", "fc2"):
                name = f"encoder.layers.{i}.mlp.{rest[0]}"
            elif rest and rest[0] == "self_attn":
                name = f"encoder.layers.{i}.self_attn.{rest[1]}"
            else:
                name = f"encoder.layers.{i}.{rest[0]}"
            arr = sd[f"{name}.{'weight' if leaf_name in ('kernel', 'scale') else 'bias'}"]
        if leaf_name == "kernel":
            arr = np.transpose(arr)
        if tuple(arr.shape) != tuple(leaf.shape):
            raise ValueError(f"CLIP shape mismatch at {'/'.join(path)}: {arr.shape} vs {leaf.shape}")
        out[path] = arr
    return traverse_util.unflatten_dict(out)
