"""Model zoo: the video UNet and its building blocks (flax linen)."""

from videop2p_tpu.models.attention import (
    AttnControl,
    BasicTransformerBlock,
    ControlledAttention,
    FrameAttention,
    Transformer3DModel,
)
from videop2p_tpu.models.layers import (
    Downsample3D,
    InflatedConv,
    ResnetBlock3D,
    TimestepEmbedding,
    Upsample3D,
    get_timestep_embedding,
)
from videop2p_tpu.models.clip import CLIPTextConfig, CLIPTextEncoder
from videop2p_tpu.models.unet import UNet3DConditionModel, UNet3DConfig
from videop2p_tpu.models.vae import AutoencoderKL, VAEConfig, decode_video, encode_video

__all__ = [
    "AttnControl",
    "BasicTransformerBlock",
    "ControlledAttention",
    "FrameAttention",
    "Transformer3DModel",
    "Downsample3D",
    "InflatedConv",
    "ResnetBlock3D",
    "TimestepEmbedding",
    "Upsample3D",
    "get_timestep_embedding",
    "UNet3DConditionModel",
    "UNet3DConfig",
    "CLIPTextConfig",
    "CLIPTextEncoder",
    "AutoencoderKL",
    "VAEConfig",
    "decode_video",
    "encode_video",
]
