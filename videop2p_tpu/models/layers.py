"""Base layers for the video UNet: pseudo-3D convs, resnet blocks, resampling,
timestep embeddings.

TPU-native re-design of /root/reference/tuneavideo/models/resnet.py. Layout is
channels-last ``(batch, frames, height, width, chan)`` — XLA's preferred conv
layout on TPU — instead of the reference's ``(b, c, f, h, w)``. The reference's
``InflatedConv3d`` (resnet.py:11-19) is a 2-D conv applied per frame via
rearrange; here the frame axis is folded into batch around a plain ``nn.Conv``,
which XLA lowers to one large MXU conv over ``B·F`` images.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

__all__ = [
    "get_timestep_embedding",
    "TimestepEmbedding",
    "TpuGroupNorm",
    "InflatedConv",
    "Upsample3D",
    "Downsample3D",
    "ResnetBlock3D",
]

Dtype = jnp.dtype


class TpuGroupNorm(nn.Module):
    """GroupNorm with an optional fused activation and a one-pass Pallas
    path (ops/groupnorm.py) on TPU where one statistics sample's slab fits
    VMEM — the stats+apply two-traversal structure XLA lowers GroupNorm to
    was 21 % of round-4 edit device time (docs/PERF_ANALYSIS.md).

    Drop-in for ``nn.GroupNorm``: identical parameter tree ('scale'/'bias'
    of shape (C,)), identical statistics semantics (per-sample per-group,
    f32 accumulation, biased variance — torch GroupNorm, which the
    reference uses throughout resnet.py / attention.py). Statistics pool
    over EVERY non-batch, non-channel axis of the input — frame-pooled on
    (B, F, H, W, C), per-frame when the caller folds frames into batch
    first (the Transformer3DModel rule, attention.py:361-368).

    ``impl``: "auto" (Pallas on TPU when the slab fits, else the XLA
    two-pass math), "xla" (always two-pass — the CPU path), "interpret"
    (kernel in interpret mode — CPU tests only).

    ``group_norm_fn``: the sharded-mesh seam
    (:func:`videop2p_tpu.parallel.make_sharded_group_norm_fn`). When set
    it OWNS the kernel decision: it is tried first with the flattened
    ``(N, rows, C)`` slab, and a ``None`` return (site not covered by the
    shard_map-wrapped kernel) falls back to the two-pass XLA math — never
    to the naked Pallas path, which pjit cannot partition.
    """

    num_groups: int = 32
    epsilon: float = 1e-5
    dtype: Dtype = jnp.float32
    act: str = "none"  # "silu" fuses the activation into the norm
    impl: str = "auto"
    group_norm_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        from videop2p_tpu.ops.groupnorm import (
            fits_fused_group_norm,
            fused_group_norm,
            group_norm_reference,
        )

        if self.impl not in ("auto", "xla", "interpret"):
            # a typo (e.g. 'pallas') must not silently select the XLA
            # fallback and change the performance path without a trace
            raise ValueError(
                f"TpuGroupNorm impl {self.impl!r} not in "
                "{'auto', 'xla', 'interpret'}"
            )
        c = x.shape[-1]
        scale = self.param("scale", nn.initializers.ones, (c,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (c,), jnp.float32)
        n = x.shape[0]
        rows = 1
        for d in x.shape[1:-1]:
            rows *= d
        x2 = x.astype(self.dtype).reshape(n, rows, c)
        if self.group_norm_fn is not None:
            y = self.group_norm_fn(
                x2, scale, bias, num_groups=self.num_groups,
                eps=self.epsilon, act=self.act,
            )
            if y is None:
                y = group_norm_reference(
                    x2, scale, bias, num_groups=self.num_groups,
                    eps=self.epsilon, act=self.act,
                )
            return y.reshape(x.shape).astype(self.dtype)
        fits = fits_fused_group_norm(rows, c, x2.dtype)
        use_kernel = self.impl == "interpret" and fits or (
            self.impl == "auto" and fits and jax.default_backend() == "tpu"
        )
        if use_kernel:
            y = fused_group_norm(
                x2, scale, bias, num_groups=self.num_groups, eps=self.epsilon,
                act=self.act, interpret=self.impl == "interpret",
            )
        else:
            y = group_norm_reference(
                x2, scale, bias, num_groups=self.num_groups, eps=self.epsilon,
                act=self.act,
            )
        return y.reshape(x.shape).astype(self.dtype)


def get_timestep_embedding(
    timesteps: jax.Array,
    embedding_dim: int,
    *,
    flip_sin_to_cos: bool = True,
    downscale_freq_shift: float = 0.0,
    max_period: int = 10000,
) -> jax.Array:
    """Sinusoidal timestep embedding, matching the diffusers ``Timesteps``
    semantics the reference UNet is configured with (unet.py:120-124:
    ``flip_sin_to_cos=True, freq_shift=0``).

    ``timesteps``: () or (B,) integer/float array → (B, embedding_dim) float32.
    """
    timesteps = jnp.atleast_1d(jnp.asarray(timesteps))
    half_dim = embedding_dim // 2
    exponent = -jnp.log(float(max_period)) * jnp.arange(half_dim, dtype=jnp.float32)
    exponent = exponent / (half_dim - downscale_freq_shift)
    emb = timesteps.astype(jnp.float32)[:, None] * jnp.exp(exponent)[None, :]
    sin, cos = jnp.sin(emb), jnp.cos(emb)
    emb = jnp.concatenate([cos, sin] if flip_sin_to_cos else [sin, cos], axis=-1)
    if embedding_dim % 2 == 1:
        emb = jnp.pad(emb, ((0, 0), (0, 1)))
    return emb


class TimestepEmbedding(nn.Module):
    """Two-layer SiLU MLP lifting the sinusoidal embedding to ``time_embed_dim``
    (the diffusers ``TimestepEmbedding`` the reference constructs at
    unet.py:125)."""

    time_embed_dim: int
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, emb: jax.Array) -> jax.Array:
        emb = nn.Dense(self.time_embed_dim, dtype=self.dtype, name="linear_1")(emb)
        emb = nn.silu(emb)
        emb = nn.Dense(self.time_embed_dim, dtype=self.dtype, name="linear_2")(emb)
        return emb


class InflatedConv(nn.Module):
    """2-D convolution applied independently to every frame
    (reference ``InflatedConv3d``, resnet.py:11-19).

    Input/output: (B, F, H, W, C). Frames fold into the batch so XLA sees one
    conv over B·F images — not a real 3-D conv, by design (temporal mixing
    happens only in temporal attention).
    """

    features: int
    kernel_size: Tuple[int, int] = (3, 3)
    strides: Tuple[int, int] = (1, 1)
    padding: int = 1
    use_bias: bool = True
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        b, f = x.shape[:2]
        x = x.reshape((b * f,) + x.shape[2:])
        x = nn.Conv(
            self.features,
            self.kernel_size,
            strides=self.strides,
            padding=[(self.padding, self.padding)] * 2,
            use_bias=self.use_bias,
            dtype=self.dtype,
            name="conv",
        )(x)
        return x.reshape((b, f) + x.shape[1:])


class Upsample3D(nn.Module):
    """Nearest ×2 spatial upsample per frame, then 3×3 conv
    (reference Upsample3D, resnet.py:22-74: scale ``[1, 2, 2]``, mode nearest)."""

    features: int
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        b, f, h, w, c = x.shape
        x = jax.image.resize(x, (b, f, h * 2, w * 2, c), method="nearest")
        return InflatedConv(self.features, dtype=self.dtype, name="conv")(x)


class Downsample3D(nn.Module):
    """Stride-2 3×3 conv per frame (reference Downsample3D, resnet.py:77-108)."""

    features: int
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        return InflatedConv(
            self.features, strides=(2, 2), padding=1, dtype=self.dtype, name="conv"
        )(x)


class ResnetBlock3D(nn.Module):
    """GN → SiLU → conv → (+time emb) → GN → SiLU → conv, with a 1×1 shortcut
    when channels change (reference ResnetBlock3D, resnet.py:111-205;
    ``time_embedding_norm="default"``: the time embedding is *added* after the
    first conv, broadcast over frames and space, resnet.py:181-184)."""

    features: int
    groups: int = 32
    eps: float = 1e-5
    dropout: float = 0.0
    dtype: Dtype = jnp.float32
    gn_impl: str = "auto"
    group_norm_fn: Optional[Callable] = None

    @nn.compact
    def __call__(
        self, x: jax.Array, temb: Optional[jax.Array] = None, deterministic: bool = True
    ) -> jax.Array:
        in_features = x.shape[-1]
        h = TpuGroupNorm(
            num_groups=self.groups, epsilon=self.eps, dtype=self.dtype,
            act="silu", impl=self.gn_impl, group_norm_fn=self.group_norm_fn,
            name="norm1",
        )(x)
        h = InflatedConv(self.features, dtype=self.dtype, name="conv1")(h)

        if temb is not None:
            temb = nn.Dense(self.features, dtype=self.dtype, name="time_emb_proj")(nn.silu(temb))
            h = h + temb[:, None, None, None, :]

        h = TpuGroupNorm(
            num_groups=self.groups, epsilon=self.eps, dtype=self.dtype,
            act="silu", impl=self.gn_impl, group_norm_fn=self.group_norm_fn,
            name="norm2",
        )(h)
        h = nn.Dropout(self.dropout)(h, deterministic=deterministic)
        h = InflatedConv(self.features, dtype=self.dtype, name="conv2")(h)

        if in_features != self.features:
            x = InflatedConv(
                self.features, kernel_size=(1, 1), padding=0, dtype=self.dtype,
                name="conv_shortcut",
            )(x)
        return x + h
