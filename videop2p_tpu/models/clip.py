"""CLIP text encoder in flax (the SD-1.x conditioning model).

The reference consumes ``transformers.CLIPTextModel`` as a frozen dependency
(/root/reference/run_tuning.py:129, run_videop2p.py:104-107). This is a
from-scratch linen implementation of the same architecture — learned token +
position embeddings, pre-LN transformer with causal masking and QuickGELU,
final LayerNorm — returning the last hidden state (B, 77, 768) the UNet
cross-attends to. Weight import from a transformers checkpoint lives in
:mod:`videop2p_tpu.models.convert` and is validated numerically against the
torch model in tests/test_convert.py.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

__all__ = ["CLIPTextConfig", "CLIPTextEncoder"]

Dtype = jnp.dtype


@dataclasses.dataclass(frozen=True)
class CLIPTextConfig:
    vocab_size: int = 49408
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    max_position_embeddings: int = 77
    layer_norm_eps: float = 1e-5

    @classmethod
    def tiny(cls, **overrides) -> "CLIPTextConfig":
        cfg = dict(
            vocab_size=128, hidden_size=16, intermediate_size=32,
            num_hidden_layers=2, num_attention_heads=2, max_position_embeddings=77,
        )
        cfg.update(overrides)
        return cls(**cfg)


def quick_gelu(x: jax.Array) -> jax.Array:
    return x * jax.nn.sigmoid(1.702 * x)


class _CLIPAttention(nn.Module):
    config: CLIPTextConfig
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array, mask: jax.Array) -> jax.Array:
        cfg = self.config
        h = cfg.num_attention_heads
        d = cfg.hidden_size // h
        b, n, _ = x.shape
        q = nn.Dense(cfg.hidden_size, dtype=self.dtype, name="q_proj")(x) * (d ** -0.5)
        k = nn.Dense(cfg.hidden_size, dtype=self.dtype, name="k_proj")(x)
        v = nn.Dense(cfg.hidden_size, dtype=self.dtype, name="v_proj")(x)
        q, k, v = (t.reshape(b, n, h, d).transpose(0, 2, 1, 3) for t in (q, k, v))
        sim = jnp.einsum("bhqd,bhkd->bhqk", q, k) + mask
        probs = jax.nn.softmax(sim.astype(jnp.float32), axis=-1).astype(self.dtype)
        out = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
        out = out.transpose(0, 2, 1, 3).reshape(b, n, cfg.hidden_size)
        return nn.Dense(cfg.hidden_size, dtype=self.dtype, name="out_proj")(out)


class _CLIPLayer(nn.Module):
    config: CLIPTextConfig
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array, mask: jax.Array) -> jax.Array:
        cfg = self.config
        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=self.dtype, name="layer_norm1")(x)
        x = x + _CLIPAttention(cfg, self.dtype, name="self_attn")(h, mask)
        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=self.dtype, name="layer_norm2")(x)
        h = nn.Dense(cfg.intermediate_size, dtype=self.dtype, name="fc1")(h)
        h = quick_gelu(h)
        h = nn.Dense(cfg.hidden_size, dtype=self.dtype, name="fc2")(h)
        return x + h


class CLIPTextEncoder(nn.Module):
    """``__call__(input_ids (B, L) int32) -> last_hidden_state (B, L, D)``."""

    config: CLIPTextConfig
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, input_ids: jax.Array) -> jax.Array:
        cfg = self.config
        b, n = input_ids.shape
        # wrap ids into the table: a no-op at the real 49408 vocab, and keeps
        # tiny smoke configs (vocab 128) finite when fed real tokenizer ids —
        # out-of-range jnp.take fills NaN outside jit
        input_ids = input_ids % cfg.vocab_size
        tok = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=self.dtype, name="token_embedding")(
            input_ids
        )
        pos = self.param(
            "position_embedding",
            nn.initializers.normal(0.02),
            (cfg.max_position_embeddings, cfg.hidden_size),
        )
        x = tok + pos[None, :n].astype(self.dtype)
        # causal mask (CLIP text transformer is autoregressive-masked)
        mask = jnp.triu(jnp.full((n, n), -jnp.inf, jnp.float32), k=1)[None, None]
        for i in range(cfg.num_hidden_layers):
            x = _CLIPLayer(cfg, self.dtype, name=f"layers_{i}")(x, mask)
        return nn.LayerNorm(
            epsilon=cfg.layer_norm_eps, dtype=self.dtype, name="final_layer_norm"
        )(x)
