"""Macro-blocks of the video UNet (reference
/root/reference/tuneavideo/models/unet_blocks.py).

Each block is a linen module over (B, F, H, W, C) activations; cross-attention
blocks thread the text context and the functional attention control. Down
blocks return their per-layer outputs for the skip connections; up blocks
consume them via channel concat (unet_blocks.py:486-488).

Gradient checkpointing is applied by the parent UNet via ``nn.remat`` around
these blocks (the reference checkpoints per resnet/attn pair inside each block,
unet_blocks.py:290-306 — block-level remat is the XLA-friendly equivalent:
coarser boundaries, same activation-memory/recompute trade).
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from videop2p_tpu.models.attention import AttnControl, Transformer3DModel
from videop2p_tpu.models.layers import Downsample3D, ResnetBlock3D, Upsample3D

__all__ = [
    "CrossAttnDownBlock3D",
    "DownBlock3D",
    "UNetMidBlock3DCrossAttn",
    "CrossAttnUpBlock3D",
    "UpBlock3D",
    "get_down_block",
    "get_up_block",
]

Dtype = jnp.dtype


class CrossAttnDownBlock3D(nn.Module):
    """[Resnet → Transformer3D] × layers, then optional downsample
    (unet_blocks.py:209-319)."""

    out_channels: int
    num_layers: int = 2
    transformer_depth: int = 1
    attn_heads: int = 8
    add_downsample: bool = True
    norm_groups: int = 32
    gn_impl: str = "auto"
    group_norm_fn: Optional[Callable] = None
    dtype: Dtype = jnp.float32
    frame_attention_fn: Optional[Callable] = None
    temporal_attention_fn: Optional[Callable] = None
    row_parallel_dot: Optional[Callable] = None
    # activation fake-quant at the transformer Dense boundaries (w8a8
    # quant mode — models/quant.py); None → byte-identical off path
    act_quant_fn: Optional[Callable] = None

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        temb: jax.Array,
        context: jax.Array,
        control: Optional[AttnControl] = None,
    ) -> Tuple[jax.Array, Tuple[jax.Array, ...]]:
        outputs = []
        for i in range(self.num_layers):
            x = ResnetBlock3D(
                self.out_channels, groups=self.norm_groups, dtype=self.dtype,
                gn_impl=self.gn_impl, group_norm_fn=self.group_norm_fn,
                name=f"resnets_{i}",
            )(x, temb)
            x = Transformer3DModel(
                heads=self.attn_heads,
                dim_head=self.out_channels // self.attn_heads,
                depth=self.transformer_depth,
                norm_groups=self.norm_groups,
                gn_impl=self.gn_impl,
                group_norm_fn=self.group_norm_fn,
                dtype=self.dtype,
                frame_attention_fn=self.frame_attention_fn,
                temporal_attention_fn=self.temporal_attention_fn,
                row_parallel_dot=self.row_parallel_dot,
                act_quant_fn=self.act_quant_fn,
                name=f"attentions_{i}",
            )(x, context=context, control=control)
            outputs.append(x)
        if self.add_downsample:
            x = Downsample3D(self.out_channels, dtype=self.dtype, name="downsample")(x)
            outputs.append(x)
        return x, tuple(outputs)


class DownBlock3D(nn.Module):
    """Resnet-only down block (unet_blocks.py:322-398)."""

    out_channels: int
    num_layers: int = 2
    add_downsample: bool = True
    norm_groups: int = 32
    gn_impl: str = "auto"
    group_norm_fn: Optional[Callable] = None
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(
        self, x: jax.Array, temb: jax.Array
    ) -> Tuple[jax.Array, Tuple[jax.Array, ...]]:
        outputs = []
        for i in range(self.num_layers):
            x = ResnetBlock3D(
                self.out_channels, groups=self.norm_groups, dtype=self.dtype,
                gn_impl=self.gn_impl, group_norm_fn=self.group_norm_fn,
                name=f"resnets_{i}",
            )(x, temb)
            outputs.append(x)
        if self.add_downsample:
            x = Downsample3D(self.out_channels, dtype=self.dtype, name="downsample")(x)
            outputs.append(x)
        return x, tuple(outputs)


class UNetMidBlock3DCrossAttn(nn.Module):
    """Resnet → [Transformer3D → Resnet] × layers (unet_blocks.py:125-206)."""

    channels: int
    num_layers: int = 1
    transformer_depth: int = 1
    attn_heads: int = 8
    norm_groups: int = 32
    gn_impl: str = "auto"
    group_norm_fn: Optional[Callable] = None
    dtype: Dtype = jnp.float32
    frame_attention_fn: Optional[Callable] = None
    temporal_attention_fn: Optional[Callable] = None
    row_parallel_dot: Optional[Callable] = None
    # activation fake-quant at the transformer Dense boundaries (w8a8
    # quant mode — models/quant.py); None → byte-identical off path
    act_quant_fn: Optional[Callable] = None

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        temb: jax.Array,
        context: jax.Array,
        control: Optional[AttnControl] = None,
    ) -> jax.Array:
        x = ResnetBlock3D(
            self.channels, groups=self.norm_groups, dtype=self.dtype,
            gn_impl=self.gn_impl, group_norm_fn=self.group_norm_fn,
            name="resnets_0"
        )(x, temb)
        for i in range(self.num_layers):
            x = Transformer3DModel(
                heads=self.attn_heads,
                dim_head=self.channels // self.attn_heads,
                depth=self.transformer_depth,
                norm_groups=self.norm_groups,
                gn_impl=self.gn_impl,
                group_norm_fn=self.group_norm_fn,
                dtype=self.dtype,
                frame_attention_fn=self.frame_attention_fn,
                temporal_attention_fn=self.temporal_attention_fn,
                row_parallel_dot=self.row_parallel_dot,
                act_quant_fn=self.act_quant_fn,
                name=f"attentions_{i}",
            )(x, context=context, control=control)
            x = ResnetBlock3D(
                self.channels, groups=self.norm_groups, dtype=self.dtype,
                gn_impl=self.gn_impl, group_norm_fn=self.group_norm_fn,
                name=f"resnets_{i + 1}",
            )(x, temb)
        return x


class CrossAttnUpBlock3D(nn.Module):
    """[skip-concat → Resnet → Transformer3D] × layers, then optional upsample
    (unet_blocks.py:401-515)."""

    out_channels: int
    num_layers: int = 3
    transformer_depth: int = 1
    attn_heads: int = 8
    add_upsample: bool = True
    norm_groups: int = 32
    gn_impl: str = "auto"
    group_norm_fn: Optional[Callable] = None
    dtype: Dtype = jnp.float32
    frame_attention_fn: Optional[Callable] = None
    temporal_attention_fn: Optional[Callable] = None
    row_parallel_dot: Optional[Callable] = None
    # activation fake-quant at the transformer Dense boundaries (w8a8
    # quant mode — models/quant.py); None → byte-identical off path
    act_quant_fn: Optional[Callable] = None

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        res_samples: Tuple[jax.Array, ...],
        temb: jax.Array,
        context: jax.Array,
        control: Optional[AttnControl] = None,
    ) -> jax.Array:
        for i in range(self.num_layers):
            x = jnp.concatenate([x, res_samples[-(i + 1)]], axis=-1)
            x = ResnetBlock3D(
                self.out_channels, groups=self.norm_groups, dtype=self.dtype,
                gn_impl=self.gn_impl, group_norm_fn=self.group_norm_fn,
                name=f"resnets_{i}",
            )(x, temb)
            x = Transformer3DModel(
                heads=self.attn_heads,
                dim_head=self.out_channels // self.attn_heads,
                depth=self.transformer_depth,
                norm_groups=self.norm_groups,
                gn_impl=self.gn_impl,
                group_norm_fn=self.group_norm_fn,
                dtype=self.dtype,
                frame_attention_fn=self.frame_attention_fn,
                temporal_attention_fn=self.temporal_attention_fn,
                row_parallel_dot=self.row_parallel_dot,
                act_quant_fn=self.act_quant_fn,
                name=f"attentions_{i}",
            )(x, context=context, control=control)
        if self.add_upsample:
            x = Upsample3D(self.out_channels, dtype=self.dtype, name="upsample")(x)
        return x


class UpBlock3D(nn.Module):
    """Resnet-only up block (unet_blocks.py:518-589)."""

    out_channels: int
    num_layers: int = 3
    add_upsample: bool = True
    norm_groups: int = 32
    gn_impl: str = "auto"
    group_norm_fn: Optional[Callable] = None
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        res_samples: Tuple[jax.Array, ...],
        temb: jax.Array,
    ) -> jax.Array:
        for i in range(self.num_layers):
            x = jnp.concatenate([x, res_samples[-(i + 1)]], axis=-1)
            x = ResnetBlock3D(
                self.out_channels, groups=self.norm_groups, dtype=self.dtype,
                gn_impl=self.gn_impl, group_norm_fn=self.group_norm_fn,
                name=f"resnets_{i}",
            )(x, temb)
        if self.add_upsample:
            x = Upsample3D(self.out_channels, dtype=self.dtype, name="upsample")(x)
        return x


_ATTN_ONLY_KWARGS = (
    "transformer_depth", "attn_heads", "frame_attention_fn", "temporal_attention_fn",
    "row_parallel_dot", "act_quant_fn",
)


def resolve_remat_policy(name):
    """jax.checkpoint_policies entry by name (None → full recompute)."""
    if name is None:
        return None
    import jax

    return getattr(jax.checkpoint_policies, name)


def _make(mod_cls, remat: bool, kwargs, policy=None):
    if remat:
        mod_cls = nn.remat(mod_cls, policy=resolve_remat_policy(policy))
    return mod_cls(**kwargs)


def get_down_block(block_type: str, *, remat: bool = False,
                   remat_policy=None, **kwargs):
    """Factory mirroring unet_blocks.py:11-65; raises on unknown types."""
    if block_type == "CrossAttnDownBlock3D":
        return _make(CrossAttnDownBlock3D, remat, kwargs, remat_policy)
    if block_type == "DownBlock3D":
        kwargs = {k: v for k, v in kwargs.items() if k not in _ATTN_ONLY_KWARGS}
        return _make(DownBlock3D, remat, kwargs, remat_policy)
    raise ValueError(f"unknown down block type: {block_type!r}")


def get_up_block(block_type: str, *, remat: bool = False,
                 remat_policy=None, **kwargs):
    """Factory mirroring unet_blocks.py:68-122; raises on unknown types."""
    if block_type == "CrossAttnUpBlock3D":
        return _make(CrossAttnUpBlock3D, remat, kwargs, remat_policy)
    if block_type == "UpBlock3D":
        kwargs = {k: v for k, v in kwargs.items() if k not in _ATTN_ONLY_KWARGS}
        return _make(UpBlock3D, remat, kwargs, remat_policy)
    raise ValueError(f"unknown up block type: {block_type!r}")
