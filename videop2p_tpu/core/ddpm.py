"""Minimal functional DDPM scheduler for Stage-1 training.

The reference consumes ``diffusers.DDPMScheduler`` only for the forward
process during tuning (`add_noise`, run_tuning.py:127,304) and as the training
target oracle (ε / v, run_tuning.py:310-315). This provides exactly that
surface, sharing the β-schedule math with :mod:`videop2p_tpu.core.ddim`.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from videop2p_tpu.core.ddim import make_beta_schedule

__all__ = ["DDPMScheduler"]


class DDPMScheduler(struct.PyTreeNode):
    alphas_cumprod: jax.Array  # (num_train_timesteps,) float32

    num_train_timesteps: int = struct.field(pytree_node=False, default=1000)
    beta_schedule: str = struct.field(pytree_node=False, default="linear")
    prediction_type: str = struct.field(pytree_node=False, default="epsilon")

    @classmethod
    def create(
        cls,
        num_train_timesteps: int = 1000,
        beta_start: float = 0.0001,
        beta_end: float = 0.02,
        beta_schedule: str = "linear",
        prediction_type: str = "epsilon",
    ) -> "DDPMScheduler":
        betas = make_beta_schedule(beta_schedule, num_train_timesteps, beta_start, beta_end)
        return cls(
            alphas_cumprod=jnp.asarray(np.cumprod(1.0 - betas).astype(np.float32)),
            num_train_timesteps=num_train_timesteps,
            beta_schedule=beta_schedule,
            prediction_type=prediction_type,
        )

    @classmethod
    def create_sd(cls, **overrides) -> "DDPMScheduler":
        """SD-1.x training schedule (the `scheduler/` subfolder the reference
        loads at run_tuning.py:127)."""
        cfg = dict(beta_start=0.00085, beta_end=0.012, beta_schedule="scaled_linear")
        cfg.update(overrides)
        return cls.create(**cfg)

    def _coeffs(self, timesteps: jax.Array, ndim: int):
        alpha_prod = self.alphas_cumprod[timesteps]
        shape = alpha_prod.shape + (1,) * (ndim - alpha_prod.ndim)
        return jnp.sqrt(alpha_prod).reshape(shape), jnp.sqrt(1.0 - alpha_prod).reshape(shape)

    def add_noise(
        self, original_samples: jax.Array, noise: jax.Array, timesteps: jax.Array
    ) -> jax.Array:
        a, b = self._coeffs(timesteps, original_samples.ndim)
        return a * original_samples + b * noise

    def get_velocity(self, sample: jax.Array, noise: jax.Array, timesteps: jax.Array) -> jax.Array:
        a, b = self._coeffs(timesteps, sample.ndim)
        return a * noise - b * sample

    def training_target(
        self, sample: jax.Array, noise: jax.Array, timesteps: jax.Array
    ) -> jax.Array:
        """The regression target for the configured prediction type
        (run_tuning.py:310-315)."""
        if self.prediction_type == "epsilon":
            return noise
        if self.prediction_type == "v_prediction":
            return self.get_velocity(sample, noise, timesteps)
        raise ValueError(f"unknown prediction_type: {self.prediction_type!r}")
