"""Core diffusion math: schedulers and noise processes (pure JAX)."""

from videop2p_tpu.core.ddim import DDIMScheduler
from videop2p_tpu.core.ddpm import DDPMScheduler
from videop2p_tpu.core.noise import DependentNoiseSampler

__all__ = ["DDIMScheduler", "DDPMScheduler", "DependentNoiseSampler"]
