"""Functional DDIM scheduler with a dependent-noise seam.

TPU-native re-design of the reference's ``DDIMScheduler_dependent``
(/root/reference/dependent_ddim.py:78-388). Differences from the reference:

  * the scheduler is an immutable pytree (`flax.struct.PyTreeNode`) — ``step``
    is a pure function safe inside ``jax.jit`` / ``lax.scan`` with traced
    timesteps;
  * instead of the scheduler *calling* a stateful sampler for η-variance noise
    (dependent_ddim.py:320-334), callers pass ``variance_noise`` explicitly
    (drawn i.i.d. or from :class:`~videop2p_tpu.core.noise.DependentNoiseSampler`)
    so randomness stays key-threaded and the step stays pure;
  * closed-form inversion steps (``next_step`` / ``prev_step``, mirroring
    /root/reference/run_videop2p.py:445-463) live on the scheduler itself;
  * every step is an fp32 island: ``model_output``/``sample`` are cast to
    float32 at entry and the αᾱ-coefficient math runs in float32 even when
    the surrounding trace is bf16 (the mixed-precision null-text program,
    pipelines/inversion.py) — trajectory fidelity must not depend on the
    caller's compute dtype. Step outputs are therefore always float32.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

__all__ = ["DDIMScheduler", "make_beta_schedule"]


def _f32(*arrays: jax.Array) -> Tuple[jax.Array, ...]:
    """The fp32-island entry cast: scheduler math stays float32 under a
    bf16 trace (no-op on float32 inputs)."""
    return tuple(jnp.asarray(a).astype(jnp.float32) for a in arrays)


def make_beta_schedule(
    schedule: str,
    num_train_timesteps: int,
    beta_start: float,
    beta_end: float,
    *,
    max_beta: float = 0.999,
) -> np.ndarray:
    """β schedule, matching dependent_ddim.py:141-154 semantics.

    ``scaled_linear`` is linear in sqrt-space (the Stable Diffusion schedule);
    ``squaredcos_cap_v2`` is the Nichol/Dhariwal cosine ᾱ schedule
    (dependent_ddim.py:49-75).
    """
    if schedule == "linear":
        betas = np.linspace(beta_start, beta_end, num_train_timesteps, dtype=np.float64)
    elif schedule == "scaled_linear":
        betas = (
            np.linspace(beta_start**0.5, beta_end**0.5, num_train_timesteps, dtype=np.float64)
            ** 2
        )
    elif schedule == "squaredcos_cap_v2":
        def alpha_bar(t: np.ndarray) -> np.ndarray:
            return np.cos((t + 0.008) / 1.008 * np.pi / 2) ** 2

        t1 = np.arange(num_train_timesteps, dtype=np.float64) / num_train_timesteps
        t2 = (np.arange(num_train_timesteps, dtype=np.float64) + 1) / num_train_timesteps
        betas = np.minimum(1.0 - alpha_bar(t2) / alpha_bar(t1), max_beta)
    else:
        raise ValueError(f"unknown beta schedule: {schedule!r}")
    return betas.astype(np.float32)


class DDIMScheduler(struct.PyTreeNode):
    """Immutable DDIM scheduler state.

    Array leaves participate in jit tracing; config fields are static.
    """

    alphas_cumprod: jax.Array  # (num_train_timesteps,) float32
    final_alpha_cumprod: jax.Array  # () float32

    num_train_timesteps: int = struct.field(pytree_node=False, default=1000)
    beta_start: float = struct.field(pytree_node=False, default=0.0001)
    beta_end: float = struct.field(pytree_node=False, default=0.02)
    beta_schedule: str = struct.field(pytree_node=False, default="linear")
    clip_sample: bool = struct.field(pytree_node=False, default=True)
    set_alpha_to_one: bool = struct.field(pytree_node=False, default=True)
    steps_offset: int = struct.field(pytree_node=False, default=0)
    prediction_type: str = struct.field(pytree_node=False, default="epsilon")

    @classmethod
    def create(
        cls,
        num_train_timesteps: int = 1000,
        beta_start: float = 0.0001,
        beta_end: float = 0.02,
        beta_schedule: str = "linear",
        clip_sample: bool = True,
        set_alpha_to_one: bool = True,
        steps_offset: int = 0,
        prediction_type: str = "epsilon",
    ) -> "DDIMScheduler":
        betas = make_beta_schedule(beta_schedule, num_train_timesteps, beta_start, beta_end)
        alphas_cumprod = np.cumprod(1.0 - betas).astype(np.float32)
        # At the t=0 boundary DDIM steps to ᾱ = 1 ("clean") or ᾱ_0
        # (dependent_ddim.py:156-166).
        final = 1.0 if set_alpha_to_one else float(alphas_cumprod[0])
        return cls(
            alphas_cumprod=jnp.asarray(alphas_cumprod),
            final_alpha_cumprod=jnp.asarray(final, dtype=jnp.float32),
            num_train_timesteps=num_train_timesteps,
            beta_start=beta_start,
            beta_end=beta_end,
            beta_schedule=beta_schedule,
            clip_sample=clip_sample,
            set_alpha_to_one=set_alpha_to_one,
            steps_offset=steps_offset,
            prediction_type=prediction_type,
        )

    @classmethod
    def from_config(cls, config) -> "DDIMScheduler":
        """Build from a diffusers ``scheduler_config.json`` dict — the Stage-2
        path loads the tuned pipeline's scheduler instead of assuming SD
        defaults (run_videop2p.py:101-114; notably the Stage-1 export writes
        ``steps_offset: 1``). Unknown keys are ignored."""
        known = (
            "num_train_timesteps", "beta_start", "beta_end", "beta_schedule",
            "clip_sample", "set_alpha_to_one", "steps_offset", "prediction_type",
        )
        kwargs = {k: config[k] for k in known if k in config}
        return cls.create(**kwargs)

    @classmethod
    def create_sd(cls, **overrides) -> "DDIMScheduler":
        """The Stable-Diffusion configuration used throughout the reference
        (run_videop2p.py:30)."""
        cfg = dict(
            beta_start=0.00085,
            beta_end=0.012,
            beta_schedule="scaled_linear",
            clip_sample=False,
            set_alpha_to_one=False,
        )
        cfg.update(overrides)
        return cls.create(**cfg)

    # ------------------------------------------------------------------ #
    # timestep grid
    # ------------------------------------------------------------------ #

    def timesteps(self, num_inference_steps: int) -> np.ndarray:
        """Descending inference timesteps (dependent_ddim.py:196-210).

        Static (numpy) because the grid shapes the scan; values feed the jitted
        step as a traced operand.
        """
        step_ratio = self.num_train_timesteps // num_inference_steps
        ts = (np.arange(num_inference_steps) * step_ratio).round()[::-1].astype(np.int64)
        return ts + self.steps_offset

    def subset_positions(self, base_steps: int, steps: int) -> np.ndarray:
        """Positions into the DESCENDING ``timesteps(base_steps)`` grid for a
        ``steps``-step walk over an EXACT subset of the base timesteps.

        The cached fast path's step-reduction seam: a ``base_steps``
        inversion trajectory holds a latent at every base grid point, so an
        edit that visits only a subset of those timesteps can still read the
        source replay (and the captured maps) exactly — no re-inversion, no
        interpolation. Leading-spaced (``floor(j·base/steps)``), so position
        0 (x_T) is always included and the subset walk starts from the same
        x_T the base walk would.
        """
        base_steps, steps = int(base_steps), int(steps)
        if not 1 <= steps <= base_steps:
            raise ValueError(
                f"steps {steps} must be in [1, base_steps={base_steps}]"
            )
        return np.floor(
            np.arange(steps) * (base_steps / steps)
        ).astype(np.int64)

    def subset_schedule(
        self, base_steps: int, steps: int
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """``(positions, timesteps, prev_timesteps)`` for a ``steps``-step
        walk over an exact subset of the ``base_steps`` grid.

        ``prev_timesteps[j]`` is where step *j* lands: the next subset
        timestep, and for the last step the base walk's own terminal target
        (``timesteps(base)[-1] − ratio`` < 0 → ``final_alpha_cumprod``), so
        every subset walk ends at the same "clean" ᾱ as the base walk. With
        ``steps == base_steps`` this reproduces the uniform rule exactly —
        ``prev_timesteps == timesteps − ratio`` — so passing these through
        ``step(..., prev_timestep=...)`` changes nothing at full step count.
        """
        positions = self.subset_positions(base_steps, steps)
        base_ts = self.timesteps(base_steps)
        ts = base_ts[positions]
        ratio = self.num_train_timesteps // base_steps
        prev = np.concatenate([ts[1:], [base_ts[-1] - ratio]])
        return positions, ts, prev

    # ------------------------------------------------------------------ #
    # shared math
    # ------------------------------------------------------------------ #

    def _alpha_prod(self, timestep: jax.Array) -> jax.Array:
        """ᾱ_t with the t<0 → final_alpha_cumprod boundary handled for traced t."""
        t = jnp.asarray(timestep)
        safe_t = jnp.clip(t, 0, self.num_train_timesteps - 1)
        return jnp.where(t >= 0, self.alphas_cumprod[safe_t], self.final_alpha_cumprod)

    def predict_x0_eps(
        self, model_output: jax.Array, timestep: jax.Array, sample: jax.Array
    ) -> Tuple[jax.Array, jax.Array]:
        """(pred_x0, pred_eps) under the configured prediction type
        (dependent_ddim.py:278-290). Computed in float32 (fp32 island)."""
        model_output, sample = _f32(model_output, sample)
        alpha_prod_t = self._alpha_prod(timestep)
        beta_prod_t = 1.0 - alpha_prod_t
        a, b = jnp.sqrt(alpha_prod_t), jnp.sqrt(beta_prod_t)
        if self.prediction_type == "epsilon":
            pred_x0 = (sample - b * model_output) / a
            pred_eps = model_output
        elif self.prediction_type == "sample":
            pred_x0 = model_output
            pred_eps = (sample - a * pred_x0) / b
        elif self.prediction_type == "v_prediction":
            pred_x0 = a * sample - b * model_output
            pred_eps = a * model_output + b * sample
        else:
            raise ValueError(f"unknown prediction_type: {self.prediction_type!r}")
        return pred_x0, pred_eps

    def variance(self, timestep: jax.Array, prev_timestep: jax.Array) -> jax.Array:
        """σ_t² pre-η (dependent_ddim.py:184-194)."""
        alpha_prod_t = self._alpha_prod(timestep)
        alpha_prod_t_prev = self._alpha_prod(prev_timestep)
        beta_prod_t = 1.0 - alpha_prod_t
        beta_prod_t_prev = 1.0 - alpha_prod_t_prev
        return (beta_prod_t_prev / beta_prod_t) * (1.0 - alpha_prod_t / alpha_prod_t_prev)

    # ------------------------------------------------------------------ #
    # reverse (denoise) step
    # ------------------------------------------------------------------ #

    def step(
        self,
        model_output: jax.Array,
        timestep: jax.Array,
        sample: jax.Array,
        num_inference_steps: int,
        *,
        eta: float = 0.0,
        variance_noise: Optional[jax.Array] = None,
        use_clipped_model_output: bool = False,
        prev_timestep: Optional[jax.Array] = None,
    ) -> Tuple[jax.Array, jax.Array]:
        """One reverse DDIM step x_t → x_{t-Δ} (dependent_ddim.py:212-341).

        Returns ``(prev_sample, pred_original_sample)``. When ``eta > 0`` the
        caller must supply ``variance_noise`` (i.i.d. normal or a draw from the
        dependent sampler — the reference's ``dependent=True`` path,
        dependent_ddim.py:320-334). Runs as an fp32 island: inputs are cast
        to float32 and the returned samples are float32 regardless of the
        caller's trace dtype.

        ``prev_timestep``: explicit landing timestep for non-uniform
        (timestep-subset, :meth:`subset_schedule`) walks; the default is the
        uniform rule ``t − num_train/num_inference_steps``.
        """
        model_output, sample = _f32(model_output, sample)
        if prev_timestep is None:
            prev_timestep = timestep - self.num_train_timesteps // num_inference_steps

        alpha_prod_t = self._alpha_prod(timestep)
        alpha_prod_t_prev = self._alpha_prod(prev_timestep)
        beta_prod_t = 1.0 - alpha_prod_t

        pred_x0, pred_eps = self.predict_x0_eps(model_output, timestep, sample)
        if self.clip_sample:
            pred_x0 = jnp.clip(pred_x0, -1.0, 1.0)

        var = self.variance(timestep, prev_timestep)
        std_dev_t = eta * jnp.sqrt(var)

        if use_clipped_model_output:
            pred_eps = (sample - jnp.sqrt(alpha_prod_t) * pred_x0) / jnp.sqrt(beta_prod_t)

        pred_sample_direction = jnp.sqrt(1.0 - alpha_prod_t_prev - std_dev_t**2) * pred_eps
        prev_sample = jnp.sqrt(alpha_prod_t_prev) * pred_x0 + pred_sample_direction

        if eta > 0:
            if variance_noise is None:
                raise ValueError("eta > 0 requires variance_noise (key-threaded by caller)")
            prev_sample = prev_sample + std_dev_t * variance_noise

        return prev_sample, pred_x0

    # ------------------------------------------------------------------ #
    # closed-form inversion steps (NullInversion.prev_step/next_step,
    # run_videop2p.py:445-463)
    # ------------------------------------------------------------------ #

    def prev_step(
        self,
        model_output: jax.Array,
        timestep: jax.Array,
        sample: jax.Array,
        num_inference_steps: int,
        *,
        prev_timestep: Optional[jax.Array] = None,
    ) -> jax.Array:
        """Deterministic (η=0, no clipping) x_t → x_{t-Δ}; the form used inside
        null-text optimization (run_videop2p.py:445-453). An fp32 island —
        usable from a bf16 trace without losing trajectory fidelity.
        ``prev_timestep`` overrides the uniform spacing rule (subset walks)."""
        model_output, sample = _f32(model_output, sample)
        if prev_timestep is None:
            prev_timestep = timestep - self.num_train_timesteps // num_inference_steps
        alpha_prod_t = self._alpha_prod(timestep)
        alpha_prod_t_prev = self._alpha_prod(prev_timestep)
        beta_prod_t = 1.0 - alpha_prod_t
        pred_x0 = (sample - jnp.sqrt(beta_prod_t) * model_output) / jnp.sqrt(alpha_prod_t)
        direction = jnp.sqrt(1.0 - alpha_prod_t_prev) * model_output
        return jnp.sqrt(alpha_prod_t_prev) * pred_x0 + direction

    def next_step(
        self,
        model_output: jax.Array,
        timestep: jax.Array,
        sample: jax.Array,
        num_inference_steps: int,
    ) -> jax.Array:
        """Forward DDIM (inversion) x_{t-Δ} → x_t (run_videop2p.py:455-463).
        An fp32 island, like :meth:`prev_step`."""
        model_output, sample = _f32(model_output, sample)
        next_timestep = timestep
        cur_timestep = jnp.minimum(
            next_timestep - self.num_train_timesteps // num_inference_steps,
            self.num_train_timesteps - 1,
        )
        alpha_prod_t = self._alpha_prod(cur_timestep)
        alpha_prod_t_next = self._alpha_prod(next_timestep)
        beta_prod_t = 1.0 - alpha_prod_t
        next_x0 = (sample - jnp.sqrt(beta_prod_t) * model_output) / jnp.sqrt(alpha_prod_t)
        direction = jnp.sqrt(1.0 - alpha_prod_t_next) * model_output
        return jnp.sqrt(alpha_prod_t_next) * next_x0 + direction

    # ------------------------------------------------------------------ #
    # forward process
    # ------------------------------------------------------------------ #

    def add_noise(
        self, original_samples: jax.Array, noise: jax.Array, timesteps: jax.Array
    ) -> jax.Array:
        """q(x_t | x_0) sampling (dependent_ddim.py:343-365)."""
        alpha_prod = self.alphas_cumprod[timesteps]
        shape = alpha_prod.shape + (1,) * (original_samples.ndim - alpha_prod.ndim)
        a = jnp.sqrt(alpha_prod).reshape(shape)
        b = jnp.sqrt(1.0 - alpha_prod).reshape(shape)
        return a * original_samples + b * noise

    def get_velocity(
        self, sample: jax.Array, noise: jax.Array, timesteps: jax.Array
    ) -> jax.Array:
        """v-prediction target (dependent_ddim.py:367-385)."""
        alpha_prod = self.alphas_cumprod[timesteps]
        shape = alpha_prod.shape + (1,) * (sample.ndim - alpha_prod.ndim)
        a = jnp.sqrt(alpha_prod).reshape(shape)
        b = jnp.sqrt(1.0 - alpha_prod).reshape(shape)
        return a * noise - b * sample

    @property
    def init_noise_sigma(self) -> float:
        """Initial latent scale (DDIM: 1.0; pipeline_tuneavideo.py:318)."""
        return 1.0
