"""Temporally-dependent (frame-correlated) Gaussian noise sampling.

TPU-native re-design of the reference's ``dependent_noise_sampler``
(/root/reference/dependent_noise.py:7-79), the fork's research object:

  * covariance over frames inside a window is Toeplitz Σ_ij = decay^|i-j|
    (dependent_noise.py:13-15);
  * windows are either independent draws concatenated (dependent_noise.py:73)
    or AR(1)-chained: n_k = √ac·n_{k-1} + √(1-ac)·ξ_k (dependent_noise.py:59-71);
  * the joint AR covariance kron(toeplitz(√ac^|i-j|), Σ) is exposed for
    likelihood-style losses (`loss_sig`, dependent_noise.py:17-20,49-52).

Instead of torch's ``MultivariateNormal`` object we factor Σ = L·Lᵀ once at
construction and draw ``z @ Lᵀ`` on device — identical distribution, a single
(f × f) matmul, fully jit/vmap-compatible, and key-threaded rather than
globally seeded. The AR chain over windows is a ``lax.scan``.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

__all__ = [
    "toeplitz_cov",
    "ar_window_cov",
    "DependentNoiseSampler",
]


def toeplitz_cov(size: int, decay_rate: float) -> np.ndarray:
    """Σ_ij = decay_rate^|i-j|  (dependent_noise.py:7-15)."""
    idx = np.arange(size)
    return np.power(float(decay_rate), np.abs(idx[:, None] - idx[None, :])).astype(np.float32)


def ar_window_cov(
    window_size: int, decay_rate: float, ar_coeff: float, num_windows: int
) -> np.ndarray:
    """Joint covariance of the AR-chained windows:
    kron(toeplitz(√ac^|i-j|), Σ_window)  (dependent_noise.py:17-20)."""
    outer = toeplitz_cov(num_windows, float(np.sqrt(ar_coeff)))
    inner = toeplitz_cov(window_size, decay_rate)
    return np.kron(outer, inner).astype(np.float32)


class DependentNoiseSampler(struct.PyTreeNode):
    """Frame-correlated noise source.

    ``sample`` draws noise with the requested shape whose frame axis carries
    the window/AR covariance structure; all other axes are i.i.d. batch axes
    (matching the reference's per-(b,c,h,w) draws, dependent_noise.py:54-77).
    """

    chol: jax.Array  # (window_size, window_size) lower Cholesky of Σ
    cov: jax.Array  # (window_size, window_size)
    cov_inv: jax.Array  # (window_size, window_size)

    num_frames: int = struct.field(pytree_node=False, default=60)
    window_size: int = struct.field(pytree_node=False, default=60)
    ar_sample: bool = struct.field(pytree_node=False, default=False)
    ar_coeff: float = struct.field(pytree_node=False, default=0.1)
    decay_rate: float = struct.field(pytree_node=False, default=0.1)

    @classmethod
    def create(
        cls,
        num_frames: int = 60,
        decay_rate: float = 0.1,
        window_size: int = 60,
        ar_sample: bool = False,
        ar_coeff: float = 0.1,
    ) -> "DependentNoiseSampler":
        if num_frames % window_size != 0:
            raise ValueError(
                f"num_frames ({num_frames}) must be divisible by window_size ({window_size})"
            )
        cov = toeplitz_cov(window_size, decay_rate)
        chol = np.linalg.cholesky(cov.astype(np.float64)).astype(np.float32)
        cov_inv = np.linalg.inv(cov.astype(np.float64)).astype(np.float32)
        return cls(
            chol=jnp.asarray(chol),
            cov=jnp.asarray(cov),
            cov_inv=jnp.asarray(cov_inv),
            num_frames=num_frames,
            window_size=window_size,
            ar_sample=ar_sample,
            ar_coeff=ar_coeff,
            decay_rate=decay_rate,
        )

    @property
    def num_windows(self) -> int:
        return self.num_frames // self.window_size

    def joint_cov(self) -> np.ndarray:
        """Full (num_frames × num_frames) covariance the sampler realizes."""
        if self.ar_sample:
            return ar_window_cov(
                self.window_size, self.decay_rate, self.ar_coeff, self.num_windows
            )
        blocks = [np.asarray(self.cov)] * self.num_windows
        out = np.zeros((self.num_frames, self.num_frames), dtype=np.float32)
        ws = self.window_size
        for i, b in enumerate(blocks):
            out[i * ws : (i + 1) * ws, i * ws : (i + 1) * ws] = b
        return out

    def sample(
        self,
        key: jax.Array,
        shape: Tuple[int, ...],
        frame_axis: int = 1,
        dtype: jnp.dtype = jnp.float32,
    ) -> jax.Array:
        """Draw correlated noise of ``shape``; ``shape[frame_axis]`` must equal
        ``num_frames``. Default ``frame_axis=1`` matches this framework's
        (b, f, h, w, c) layout."""
        frame_axis = frame_axis % len(shape)
        if shape[frame_axis] != self.num_frames:
            raise ValueError(
                f"shape[{frame_axis}]={shape[frame_axis]} != num_frames={self.num_frames}"
            )
        batch_shape = tuple(s for i, s in enumerate(shape) if i != frame_axis)
        nw, ws = self.num_windows, self.window_size

        z = jax.random.normal(key, batch_shape + (nw, ws), dtype=jnp.float32)
        # per-window MVN(0, Σ): z @ Lᵀ
        w = jnp.einsum("...nw,fw->...nf", z, self.chol)

        if self.ar_sample and nw > 1:
            sq_ac = float(np.sqrt(self.ar_coeff))
            sq_1m = float(np.sqrt(1.0 - self.ar_coeff))
            w_first = w[..., 0, :]
            w_rest = jnp.moveaxis(w[..., 1:, :], -2, 0)  # (nw-1, ..., ws)

            def chain(prev, xi):
                cur = sq_ac * prev + sq_1m * xi
                return cur, cur

            _, chained = jax.lax.scan(chain, w_first, w_rest)
            w = jnp.concatenate(
                [w_first[..., None, :], jnp.moveaxis(chained, 0, -2)], axis=-2
            )

        noise = w.reshape(batch_shape + (self.num_frames,))
        noise = jnp.moveaxis(noise, -1, frame_axis)
        return noise.astype(dtype)

    def sample_like(self, key: jax.Array, x: jax.Array, frame_axis: int = 1) -> jax.Array:
        """Shape/dtype-matched draw (the reference's `sample(model_output)`
        call pattern, dependent_ddim.py:324)."""
        return self.sample(key, x.shape, frame_axis=frame_axis, dtype=x.dtype)
