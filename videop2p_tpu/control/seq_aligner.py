"""Prompt token alignment → attention-map mappers (host-side, pure numpy).

Re-implementation of the reference's seq_aligner.py (itself from
google/prompt-to-prompt): Needleman-Wunsch global alignment over token ids
produces, for each edited prompt, a per-token source index (+ validity alphas)
used by AttentionRefine, and a soft (77×77) permutation matrix used by
AttentionReplace. Outputs are fixed-shape numpy arrays that feed straight into
jitted edit functions.

Semantics preserved exactly (incl. tie-breaking): scoring gap=0 / match=1 /
mismatch=-1 and traceback preference left > up > diag
(/root/reference/seq_aligner.py:63-78); refinement padding maps positions past
the target sequence to themselves (seq_aligner.py:115-119); replacement
requires equal word counts and spreads mass 1/|target| over multi-token
targets (seq_aligner.py:154-187).
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

from videop2p_tpu.utils.tokenizers import MAX_NUM_WORDS, Tokenizer
from videop2p_tpu.control.schedules import get_word_inds

__all__ = [
    "global_align",
    "aligned_target_to_source",
    "get_refinement_mapper",
    "get_replacement_mapper",
]

GAP, MATCH, MISMATCH = 0, 1, -1
# traceback codes
_LEFT, _UP, _DIAG, _STOP = 1, 2, 3, 4


def global_align(x: Sequence[int], y: Sequence[int]) -> Tuple[np.ndarray, np.ndarray]:
    """Needleman-Wunsch DP over two id sequences.

    Returns (score matrix, traceback matrix) with the reference's exact
    initialization and tie-breaking (seq_aligner.py:48-78).
    """
    nx, ny = len(x), len(y)
    score = np.zeros((nx + 1, ny + 1), dtype=np.int32)
    score[0, 1:] = (np.arange(ny) + 1) * GAP
    score[1:, 0] = (np.arange(nx) + 1) * GAP
    trace = np.zeros((nx + 1, ny + 1), dtype=np.int32)
    trace[0, 1:] = _LEFT
    trace[1:, 0] = _UP
    trace[0, 0] = _STOP

    xa = np.asarray(x)
    ya = np.asarray(y)
    for i in range(1, nx + 1):
        # vectorized over j would break the left-dependency; keep the inner
        # loop in numpy scalars (prompts are <77 tokens — negligible cost)
        for j in range(1, ny + 1):
            left = score[i, j - 1] + GAP
            up = score[i - 1, j] + GAP
            diag = score[i - 1, j - 1] + (MATCH if xa[i - 1] == ya[j - 1] else MISMATCH)
            best = max(left, up, diag)
            score[i, j] = best
            if best == left:
                trace[i, j] = _LEFT
            elif best == up:
                trace[i, j] = _UP
            else:
                trace[i, j] = _DIAG
    return score, trace


def aligned_target_to_source(
    x: Sequence[int], y: Sequence[int], trace: np.ndarray
) -> np.ndarray:
    """(len(y), 2) array of (target_pos, source_pos-or--1) pairs from the
    traceback (seq_aligner.py:81-106)."""
    i, j = len(x), len(y)
    pairs: List[Tuple[int, int]] = []
    while i > 0 or j > 0:
        code = trace[i, j]
        if code == _DIAG:
            i -= 1
            j -= 1
            pairs.append((j, i))
        elif code == _LEFT:
            j -= 1
            pairs.append((j, -1))
        elif code == _UP:
            i -= 1
        else:  # _STOP
            break
    pairs.reverse()
    return np.asarray(pairs, dtype=np.int64).reshape(-1, 2)


def _mapper_for_pair(
    x: str, y: str, tokenizer: Tokenizer, max_len: int
) -> Tuple[np.ndarray, np.ndarray]:
    """Per-token source index + validity alphas for one (source, target) pair
    (seq_aligner.py:109-120)."""
    x_ids = tokenizer.encode(x)
    y_ids = tokenizer.encode(y)
    _, trace = global_align(x_ids, y_ids)
    pairs = aligned_target_to_source(x_ids, y_ids, trace)

    alphas = np.ones(max_len, dtype=np.float32)
    alphas[: pairs.shape[0]] = (pairs[:, 1] != -1).astype(np.float32)
    mapper = np.zeros(max_len, dtype=np.int64)
    mapper[: pairs.shape[0]] = pairs[:, 1]
    mapper[pairs.shape[0] :] = len(y_ids) + np.arange(max_len - len(y_ids))
    return mapper, alphas


def get_refinement_mapper(
    prompts: Sequence[str], tokenizer: Tokenizer, max_len: int = MAX_NUM_WORDS
) -> Tuple[np.ndarray, np.ndarray]:
    """Stacked refine mappers/alphas for prompts[1:] against prompts[0]
    (seq_aligner.py:123-130). Shapes: (n_edits, max_len) each."""
    mappers, alphas = [], []
    for target in prompts[1:]:
        m, a = _mapper_for_pair(prompts[0], target, tokenizer, max_len)
        mappers.append(m)
        alphas.append(a)
    return np.stack(mappers), np.stack(alphas)


def _replacement_mapper_for_pair(
    x: str, y: str, tokenizer: Tokenizer, max_len: int
) -> np.ndarray:
    """(max_len, max_len) soft permutation for a word-swap edit
    (seq_aligner.py:154-187). Requires equal word counts."""
    words_x = x.split(" ")
    words_y = y.split(" ")
    if len(words_x) != len(words_y):
        raise ValueError(
            "attention replacement edits need equal word counts, got "
            f"{len(words_x)} vs {len(words_y)} — use a refine edit instead"
        )
    swapped = [i for i in range(len(words_y)) if words_y[i] != words_x[i]]
    inds_source = [get_word_inds(x, i, tokenizer) for i in swapped]
    inds_target = [get_word_inds(y, i, tokenizer) for i in swapped]

    mapper = np.zeros((max_len, max_len), dtype=np.float32)
    i = j = 0
    cur = 0
    while i < max_len and j < max_len:
        if cur < len(inds_source) and len(inds_source[cur]) and inds_source[cur][0] == i:
            src, tgt = inds_source[cur], inds_target[cur]
            if len(src) == len(tgt):
                mapper[src, tgt] = 1.0
            else:
                for t in tgt:
                    mapper[src, t] = 1.0 / len(tgt)
            cur += 1
            i += len(src)
            j += len(tgt)
        elif cur < len(inds_source):
            mapper[i, j] = 1.0
            i += 1
            j += 1
        else:
            mapper[j, j] = 1.0
            i += 1
            j += 1
    return mapper


def get_replacement_mapper(
    prompts: Sequence[str], tokenizer: Tokenizer, max_len: int = MAX_NUM_WORDS
) -> np.ndarray:
    """Stacked (n_edits, max_len, max_len) replace mappers
    (seq_aligner.py:191-197)."""
    return np.stack(
        [_replacement_mapper_for_pair(prompts[0], t, tokenizer, max_len) for t in prompts[1:]]
    )
