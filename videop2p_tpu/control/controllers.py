"""Functional prompt-to-prompt attention control.

The reference implements control as monkey-patched attention forwards with
hidden step/layer counters (ptp_utils.py:188-255, run_videop2p.py:196-410).
Here control is a *pure function* over attention probabilities:

    probs' = control_attention(probs, ctx, is_cross=..., step_index=...)

with all schedule state precomputed into a :class:`ControlContext` pytree and
the step index supplied by the enclosing ``lax.scan``. Controlled sites are
the text cross-attention and the temporal attention — NOT the spatial frame
attention — matching the reference's patch rule which only rebinds modules
named ``CrossAttention`` (ptp_utils.py:236-239; see SURVEY §3.4).

Edit semantics preserved:
  * only the conditional (CFG) half is edited (run_videop2p.py:212-218);
  * cross-attention: base-stream maps are mapped into each edit stream
    (replace: soft 77×77 permutation, run_videop2p.py:331-339; refine:
    per-token gather + alpha blend, :342-354), optionally reweighted by a
    per-word equalizer (:357-369), then time-gated by cross_replace_alpha
    (:311-313);
  * temporal ("self") attention: base maps broadcast to every edit stream
    inside the [lo, hi) step window (:293-298, :306, :314-315).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from videop2p_tpu.control import seq_aligner
from videop2p_tpu.control.local_blend import LocalBlendConfig, make_local_blend
from videop2p_tpu.control.schedules import get_time_words_attention_alpha, get_word_inds
from videop2p_tpu.utils.tokenizers import MAX_NUM_WORDS, Tokenizer

__all__ = [
    "ControlContext",
    "make_controller",
    "make_spatial_replace_controller",
    "control_attention",
    "get_equalizer",
]


class ControlContext(struct.PyTreeNode):
    """All state an attention edit needs, as one pytree.

    ``kind`` selects the cross edit; array fields not used by that kind are
    None. ``num_prompts`` counts conditional streams (source + edits).
    """

    cross_replace_alpha: jax.Array  # (num_steps+1, n_edits, 1, 1, 77)
    refine_mapper: Optional[jax.Array] = None  # (n_edits, 77) int32
    refine_alphas: Optional[jax.Array] = None  # (n_edits, 77)
    replace_mapper: Optional[jax.Array] = None  # (n_edits, 77, 77)
    equalizer: Optional[jax.Array] = None  # (n_edits, 77)
    blend: Optional[LocalBlendConfig] = None

    # "replace" | "refine" | "empty" (no attention edit — the reference's
    # EmptyControl/SpatialReplace base, run_videop2p.py:183,235)
    kind: str = struct.field(pytree_node=False, default="refine")
    num_prompts: int = struct.field(pytree_node=False, default=2)
    self_replace_range: Tuple[int, int] = struct.field(pytree_node=False, default=(0, 0))
    # SpatialReplace (run_videop2p.py:235-246): while step < this bound the
    # edited streams' latents are overwritten with the source stream's after
    # each scheduler step; 0 disables
    spatial_replace_until: int = struct.field(pytree_node=False, default=0)

    @property
    def n_edits(self) -> int:
        return self.num_prompts - 1


def get_equalizer(
    text: str,
    words: Sequence[str],
    values: Sequence[float],
    tokenizer: Tokenizer,
    max_len: int = MAX_NUM_WORDS,
) -> np.ndarray:
    """Per-token attention rescale factors (run_videop2p.py:372-381).

    The reference silently no-ops on two misconfigurations: a word that
    does not tokenize to any position of ``text`` writes nothing
    (``eq[:, []] = val``), and a ``words``/``values`` length mismatch is
    truncated by ``zip``. Both mean the requested reweight never happens —
    raise instead, with the offending word/lengths in the message.
    """
    eq = np.ones((1, max_len), dtype=np.float32)
    if isinstance(words, str):
        words = (words,)
    if isinstance(values, (int, float)):
        values = (values,)
    words = list(words)
    values = list(values)
    if len(words) != len(values):
        raise ValueError(
            f"equalizer words/values length mismatch: {len(words)} word(s) "
            f"{words!r} vs {len(values)} value(s) {values!r}"
        )
    for word, val in zip(words, values):
        inds = get_word_inds(text, word, tokenizer)
        if len(inds) == 0:
            raise ValueError(
                f"equalizer word {word!r} does not tokenize to any position "
                f"of the edit prompt {text!r} — the reweight would silently "
                "never apply"
            )
        eq[:, inds] = float(val)
    return eq


def make_controller(
    prompts: Sequence[str],
    tokenizer: Tokenizer,
    num_steps: int,
    *,
    is_replace_controller: bool,
    cross_replace_steps,
    self_replace_steps,
    blend_words: Optional[Tuple[Sequence[str], Sequence[str]]] = None,
    equalizer_params: Optional[Dict] = None,
    mask_th: Tuple[float, float] = (0.3, 0.3),
    start_blend: float = 0.2,
) -> ControlContext:
    """Build the edit context for a pair/list of prompts
    (run_videop2p.py:397-410).

    Word-swap edits use a replace controller, otherwise refine; an optional
    equalizer adds a reweight stage; ``blend_words`` adds a LocalBlend mask.
    """
    n_prompts = len(prompts)
    if n_prompts < 2:
        raise ValueError(
            "attention control needs a source prompt plus at least one edit "
            f"prompt, got {n_prompts} prompt(s)"
        )
    cra = get_time_words_attention_alpha(prompts, num_steps, cross_replace_steps, tokenizer)

    refine_mapper = refine_alphas = replace_mapper = None
    if is_replace_controller:
        replace_mapper = jnp.asarray(seq_aligner.get_replacement_mapper(prompts, tokenizer))
        kind = "replace"
    else:
        m, a = seq_aligner.get_refinement_mapper(prompts, tokenizer)
        refine_mapper = jnp.asarray(m.astype(np.int32))
        refine_alphas = jnp.asarray(a)
        kind = "refine"

    equalizer = None
    if equalizer_params is not None:
        eq = get_equalizer(
            prompts[1], equalizer_params["words"], equalizer_params["values"], tokenizer
        )
        # one equalizer row per edit stream (reference computes it from
        # prompts[1] and applies it to all, run_videop2p.py:362)
        equalizer = jnp.asarray(np.broadcast_to(eq, (n_prompts - 1, eq.shape[1])).copy())

    blend = None
    if blend_words is not None:
        blend = make_local_blend(
            prompts, blend_words, tokenizer, num_steps,
            th=mask_th, start_blend=start_blend,
        )

    if isinstance(self_replace_steps, (int, float)):
        self_replace_steps = (0.0, float(self_replace_steps))
    srr = (int(num_steps * self_replace_steps[0]), int(num_steps * self_replace_steps[1]))

    return ControlContext(
        cross_replace_alpha=jnp.asarray(cra),
        refine_mapper=refine_mapper,
        refine_alphas=refine_alphas,
        replace_mapper=replace_mapper,
        equalizer=equalizer,
        blend=blend,
        kind=kind,
        num_prompts=n_prompts,
        self_replace_range=srr,
    )


def make_spatial_replace_controller(
    stop_inject: float,
    num_steps: int,
    *,
    num_prompts: int = 2,
) -> ControlContext:
    """SpatialReplace (run_videop2p.py:235-246): no attention edits; for the
    first ``int((1 − stop_inject)·num_steps)`` steps every edited stream's
    latent is replaced with the source stream's after the scheduler step."""
    return ControlContext(
        cross_replace_alpha=jnp.zeros(
            (num_steps + 1, max(num_prompts - 1, 1), 1, 1, MAX_NUM_WORDS)
        ),
        kind="empty",
        num_prompts=num_prompts,
        self_replace_range=(0, 0),
        spatial_replace_until=int((1.0 - stop_inject) * num_steps),
    )


# --------------------------------------------------------------------- #
# edit functions (operate on the conditional half)
# --------------------------------------------------------------------- #


def _edit_cross(
    base: jax.Array, repl: jax.Array, ctx: ControlContext, step_index: jax.Array
) -> jax.Array:
    """base: (F,H,Q,W) source-stream cross maps; repl: (E,F,H,Q,W) edit
    streams. Returns the edited replacement streams (E,F,H,Q,W)."""
    if ctx.kind == "replace":
        new = jnp.einsum("fhqw,ewn->efhqn", base, ctx.replace_mapper)
    elif ctx.kind == "refine":
        gathered = jax.vmap(lambda m: jnp.take(base, m, axis=-1))(ctx.refine_mapper)
        al = ctx.refine_alphas[:, None, None, None, :]
        new = gathered * al + repl * (1.0 - al)
    else:
        raise ValueError(f"unknown cross edit kind: {ctx.kind!r}")

    if ctx.equalizer is not None:
        new = new * ctx.equalizer[:, None, None, None, :]

    # time gate: (E, 1, 1, W) → (E, 1, 1, 1, W)
    alpha_words = ctx.cross_replace_alpha[step_index][:, :, :, None, :]
    return new * alpha_words + (1.0 - alpha_words) * repl


def _edit_temporal(
    base: jax.Array, repl: jax.Array, ctx: ControlContext, step_index: jax.Array
) -> jax.Array:
    """base: (D,H,F,F) source-stream temporal maps; repl: (E,D,H,F,F) edit
    streams. Returns the edited replacement streams.

    Frame counts are always ≤ 32² so the reference's query-size guard
    (run_videop2p.py:294) is unconditionally true.
    """
    lo, hi = ctx.self_replace_range
    active = jnp.logical_and(step_index >= lo, step_index < hi)
    broadcast = jnp.broadcast_to(base[None], repl.shape)
    return jnp.where(active, broadcast, repl)


def control_attention(
    probs: jax.Array,
    ctx: Optional[ControlContext],
    *,
    is_cross: bool,
    step_index: jax.Array,
    video_length: int,
    num_uncond: int = -1,
    base_map: Optional[jax.Array] = None,
) -> jax.Array:
    """Apply the edit to full-batch attention probabilities.

    Layouts (uncond streams first, matching the CFG batch of
    pipeline_tuneavideo.py:235), with U uncond + P cond streams:
      cross:    ((U+P)·F, H, Q, W)  — frames folded into batch
      temporal: ((U+P)·D, H, F, F)  — spatial positions folded into batch
    Only the conditional streams are edited (run_videop2p.py:217-218). The
    default U = P is the reference's CFG batch; fast mode drops the source
    stream's unused uncond (U = P−1), and cond-only forwards pass U = 0.

    ``base_map``: cached-source mode — the source stream is NOT in the batch
    (cond streams are the P−1 edits only) and its maps for this site/step come
    from this array instead: (F, H, Q, W) for cross sites, (D, H, F, F) for
    temporal sites (captured during DDIM inversion; see
    pipelines.ddim_inversion_captured).
    """
    if ctx is None or ctx.kind == "empty":
        return probs
    P = ctx.num_prompts
    U = ctx.num_prompts if num_uncond < 0 else num_uncond
    ncond = P if base_map is None else P - 1
    B, H, Q, K = probs.shape
    if B % (U + ncond):
        raise ValueError(
            f"attention batch {B} does not factor into {U} uncond + {ncond} cond streams"
        )
    inner = B // (U + ncond)  # F for cross sites, D (=h·w) for temporal sites
    if is_cross and inner != video_length:
        raise ValueError(
            f"cross-attention batch {B} does not factor as ({U}+{ncond})·{video_length} "
            "(uncond+cond streams × frames) — batch layout mismatch"
        )
    if not is_cross and (Q != video_length or K != video_length):
        raise ValueError(
            f"temporal attention maps must be ({video_length}×{video_length}), got ({Q}×{K})"
        )

    split = probs.reshape(U + ncond, inner, H, Q, K)
    cond = split[U:]
    if base_map is None:
        base, repl = cond[0], cond[1:]
    else:
        if base_map.shape != (inner, H, Q, K):
            raise ValueError(
                f"cached base map shape {base_map.shape} does not match the "
                f"site's per-stream probability shape {(inner, H, Q, K)}"
            )
        base, repl = base_map.astype(probs.dtype), cond
    if is_cross:
        edited = _edit_cross(base, repl, ctx, step_index)
    else:
        edited = _edit_temporal(base, repl, ctx, step_index)
    if base_map is None:
        edited = jnp.concatenate([base[None], edited], axis=0)
    out = jnp.concatenate([split[:U], edited], axis=0)
    return out.reshape(B, H, Q, K)
