"""LocalBlend: word-localized latent blending from stored cross-attention.

Functional re-design of the reference LocalBlend (run_videop2p.py:129-181):
per-frame spatial masks are derived from the running sum of the 16×16-res
cross-attention maps (the reference's `down_cross[2:4] + up_cross[:3]` sites,
run_videop2p.py:145), thresholded, unioned with the source-stream mask, and
used to pull the edited latents back toward the source outside the masked
region. The reference hard-codes 8 frames and 16×16 (run_videop2p.py:146);
here both are parametric.

The map accumulator lives in the sampling scan's carry (the reference keeps it
in the controller's mutable `attention_store`, summed across steps in
`between_steps`, run_videop2p.py:261-268 — scale-invariant here because the
mask is max-normalized before thresholding).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from videop2p_tpu.control.schedules import get_word_inds
from videop2p_tpu.utils.tokenizers import MAX_NUM_WORDS, Tokenizer

__all__ = ["LocalBlendConfig", "make_local_blend", "local_blend", "blend_mask"]


class LocalBlendConfig(struct.PyTreeNode):
    alpha_layers: jax.Array  # (P, 1, 77) word mask per prompt stream
    substruct_layers: Optional[jax.Array] = None  # (P, 1, 77)
    start_blend: int = struct.field(pytree_node=False, default=10)
    th: Tuple[float, float] = struct.field(pytree_node=False, default=(0.3, 0.3))


def _word_alpha_layers(
    prompts: Sequence[str], words_per_prompt, tokenizer: Tokenizer
) -> np.ndarray:
    layers = np.zeros((len(prompts), 1, MAX_NUM_WORDS), dtype=np.float32)
    for i, (prompt, words) in enumerate(zip(prompts, words_per_prompt)):
        if isinstance(words, str):
            words = [words]
        for word in words:
            inds = get_word_inds(prompt, word, tokenizer)
            layers[i, :, inds] = 1.0
    return layers


def make_local_blend(
    prompts: Sequence[str],
    words: Tuple[Sequence[str], Sequence[str]],
    tokenizer: Tokenizer,
    num_steps: int,
    *,
    substruct_words=None,
    start_blend: float = 0.2,
    th: Tuple[float, float] = (0.3, 0.3),
) -> LocalBlendConfig:
    """Build the blend config (run_videop2p.py:157-180)."""
    alpha_layers = jnp.asarray(_word_alpha_layers(prompts, words, tokenizer))
    substruct = None
    if substruct_words is not None:
        substruct = jnp.asarray(_word_alpha_layers(prompts, substruct_words, tokenizer))
    return LocalBlendConfig(
        alpha_layers=alpha_layers,
        substruct_layers=substruct,
        start_blend=int(start_blend * num_steps),
        th=th,
    )


def _max_pool_3x3(x: jax.Array) -> jax.Array:
    """3×3 stride-1 same-padded max pool over the last two axes
    (k=1 in run_videop2p.py:132-135)."""
    return jax.lax.reduce_window(
        x,
        -jnp.inf,
        jax.lax.max,
        window_dimensions=(1,) * (x.ndim - 2) + (3, 3),
        window_strides=(1,) * x.ndim,
        padding=[(0, 0)] * (x.ndim - 2) + [(1, 1), (1, 1)],
    )


def _get_mask(
    maps: jax.Array,
    word_layers: jax.Array,
    use_pool: bool,
    out_hw: Tuple[int, int],
    th: Tuple[float, float],
) -> jax.Array:
    """Boolean (P, F, h, w) mask from accumulated maps
    (run_videop2p.py:131-140).

    ``maps``: (P, F, S, r, r, 77) — S stacks the contributing sites (head-mean;
    head-averaging commutes with the word-sum + site-mean the reference takes
    over its concatenated per-head maps).
    """
    sel = (maps * word_layers[:, None, None, None, None, :]).sum(-1).mean(2)  # (P,F,r,r)
    if use_pool:
        sel = _max_pool_3x3(sel)
    P, F = sel.shape[:2]
    mask = jax.image.resize(sel, (P, F) + tuple(out_hw), method="nearest")
    mask = mask / (mask.max(axis=(-2, -1), keepdims=True) + 1e-20)
    mask = mask > th[1 - int(use_pool)]
    mask = jnp.logical_or(mask[:1], mask)  # union with the source-stream mask
    return mask


def blend_mask(
    maps: jax.Array, cfg: LocalBlendConfig, out_hw: Tuple[int, int]
) -> jax.Array:
    """The boolean word mask LocalBlend applies, as its own seam —
    (P, F, h, w) from the (P, F, S, r, r, 77) running-sum maps. Factored
    out of :func:`local_blend` (identical math, so the blend program is
    unchanged) so the attention-observability capture can record the mask
    time series / coverage fraction the blend actually used."""
    mask = _get_mask(maps, cfg.alpha_layers[:, 0, :], True, out_hw, cfg.th)
    if cfg.substruct_layers is not None:
        sub = _get_mask(maps, cfg.substruct_layers[:, 0, :], False, out_hw, cfg.th)
        mask = jnp.logical_and(mask, jnp.logical_not(sub))
    return mask


def local_blend(
    x_t: jax.Array,
    maps: jax.Array,
    cfg: LocalBlendConfig,
    step_index: jax.Array,
) -> jax.Array:
    """Blend edited latents toward the source outside the word mask
    (run_videop2p.py:142-155).

    ``x_t``: (P, F, h, w, C) latents (source stream first);
    ``maps``: (P, F, S, r, r, 77) running-sum cross-attention maps.
    Active once ``step_index >= start_blend`` (the reference's counter>start
    gate, run_videop2p.py:143-144).
    """
    mask = blend_mask(maps, cfg, x_t.shape[2:4])
    maskf = mask.astype(x_t.dtype)[..., None]  # (P,F,h,w,1)
    blended = x_t[:1] + maskf * (x_t - x_t[:1])
    active = step_index >= cfg.start_blend
    return jnp.where(active, blended, x_t)
