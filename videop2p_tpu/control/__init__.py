"""Attention control (prompt-to-prompt) layer — pure functions, no hooks."""

from videop2p_tpu.control.seq_aligner import (
    get_refinement_mapper,
    get_replacement_mapper,
)
from videop2p_tpu.control.schedules import (
    get_word_inds,
    get_time_words_attention_alpha,
)
from videop2p_tpu.control.controllers import (
    ControlContext,
    get_equalizer,
    make_controller,
    make_spatial_replace_controller,
    control_attention,
)
from videop2p_tpu.control.local_blend import (
    LocalBlendConfig,
    blend_mask,
    local_blend,
    make_local_blend,
)

__all__ = [
    "get_refinement_mapper",
    "get_replacement_mapper",
    "get_word_inds",
    "get_time_words_attention_alpha",
    "ControlContext",
    "get_equalizer",
    "make_controller",
    "make_spatial_replace_controller",
    "control_attention",
    "LocalBlendConfig",
    "make_local_blend",
    "local_blend",
    "blend_mask",
]
