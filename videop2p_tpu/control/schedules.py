"""Word→token index mapping and time-dependent cross-replace alpha schedules.

Host-side (numpy) precomputation mirroring ptp_utils.py:258-310: the whole
per-step schedule is materialized as one fixed-shape array up front, which is
already the jit-friendly representation — the scan body just indexes it with
the (traced) step counter.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple, Union

import numpy as np

from videop2p_tpu.utils.tokenizers import MAX_NUM_WORDS, Tokenizer

__all__ = ["get_word_inds", "update_alpha_time_word", "get_time_words_attention_alpha"]

Bounds = Union[float, Tuple[float, float]]


def get_word_inds(text: str, word_place: Union[int, str], tokenizer: Tokenizer) -> np.ndarray:
    """Token positions (1-based, after BOS) covering the given word of ``text``
    (ptp_utils.py:258-276).

    ``word_place`` is either a word-index into ``text.split(' ')`` or a word
    string (all occurrences). Handles words split into multiple subword tokens
    by walking the decoded pieces and matching accumulated characters.
    """
    split_text = text.split(" ")
    if isinstance(word_place, str):
        places = [i for i, word in enumerate(split_text) if word_place == word]
    else:
        places = [int(word_place)]
    out = []
    if places:
        pieces = [tokenizer.decode_token(t) for t in tokenizer.encode(text)][1:-1]
        cur_len, ptr = 0, 0
        for i, piece in enumerate(pieces):
            cur_len += len(piece)
            if ptr in places:
                out.append(i + 1)
            if ptr < len(split_text) and cur_len >= len(split_text[ptr]):
                ptr += 1
                cur_len = 0
    return np.asarray(out, dtype=np.int64)


def update_alpha_time_word(
    alpha: np.ndarray,
    bounds: Bounds,
    prompt_ind: int,
    word_inds: Optional[np.ndarray] = None,
) -> np.ndarray:
    """In-place write of the 0/1 step-window for one edit stream
    (ptp_utils.py:279-289)."""
    if isinstance(bounds, (int, float)):
        bounds = (0.0, float(bounds))
    start, end = int(bounds[0] * alpha.shape[0]), int(bounds[1] * alpha.shape[0])
    if word_inds is None:
        word_inds = np.arange(alpha.shape[2])
    alpha[:start, prompt_ind, word_inds] = 0
    alpha[start:end, prompt_ind, word_inds] = 1
    alpha[end:, prompt_ind, word_inds] = 0
    return alpha


def get_time_words_attention_alpha(
    prompts: Sequence[str],
    num_steps: int,
    cross_replace_steps: Union[Bounds, Dict[str, Bounds]],
    tokenizer: Tokenizer,
    max_num_words: int = MAX_NUM_WORDS,
) -> np.ndarray:
    """Per-(step, edit, word) cross-attention replacement gate, shape
    ``(num_steps + 1, n_edits, 1, 1, max_num_words)`` (ptp_utils.py:292-310).

    ``cross_replace_steps`` may be a scalar/range ``default_`` plus per-word
    overrides keyed by the word string.
    """
    if not isinstance(cross_replace_steps, dict):
        cross_replace_steps = {"default_": cross_replace_steps}
    if "default_" not in cross_replace_steps:
        cross_replace_steps["default_"] = (0.0, 1.0)

    n_edits = len(prompts) - 1
    alpha = np.zeros((num_steps + 1, n_edits, max_num_words), dtype=np.float32)
    for i in range(n_edits):
        alpha = update_alpha_time_word(alpha, cross_replace_steps["default_"], i)
    for key, bounds in cross_replace_steps.items():
        if key == "default_":
            continue
        for i in range(n_edits):
            inds = get_word_inds(prompts[i + 1], key, tokenizer)
            if len(inds) > 0:
                alpha = update_alpha_time_word(alpha, bounds, i, inds)
    return alpha.reshape(num_steps + 1, n_edits, 1, 1, max_num_words)
