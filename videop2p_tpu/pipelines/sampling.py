"""The attention-controlled denoising loop (Stage-2 editing / validation
sampling).

TPU-native re-design of ``TuneAVideoPipeline.__call__``'s denoise loop
(/root/reference/tuneavideo/pipelines/pipeline_tuneavideo.py:321-441) as one
``lax.scan`` under ``jit``:

  * CFG batch ``[uncond×P, cond×P]`` (pipeline_tuneavideo.py:235);
  * per-step null-embedding injection — the optimized uncond embedding for
    step *i* replaces the static one (pipeline_tuneavideo.py:399-403);
  * fast-mode source branch: the source stream's prediction is its cond-only
    output so DDIM inversion replays exactly (pipeline_tuneavideo.py:412-415);
  * scheduler step with optional η-variance noise from the dependent sampler
    (dependent_ddim.py:320-334), key-threaded;
  * the controller sees every text-cross/temporal attention site via the
    functional control context, and LocalBlend runs as the step callback on a
    running sum of blend-site maps carried through the scan
    (pipeline_tuneavideo.py:423-424, run_videop2p.py:261-291).

The pipeline operates purely in latent space; VAE encode/decode and text
encoding are the caller's (CLI's) concern — that keeps this scan free of
host I/O and lets the whole edit jit to one XLA program.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from videop2p_tpu.control.controllers import ControlContext
from videop2p_tpu.control.local_blend import blend_mask, local_blend
from videop2p_tpu.core.ddim import DDIMScheduler
from videop2p_tpu.core.noise import DependentNoiseSampler
from videop2p_tpu.models.attention import AttnControl
from videop2p_tpu.obs.attention import ATTN_HEAT_RES, attn_step_record
from videop2p_tpu.obs.telemetry import latent_stats
from videop2p_tpu.pipelines.cached import CachedSource
from videop2p_tpu.pipelines.stores import blend_maps_from_store

__all__ = ["edit_sample", "make_unet_fn", "official_edit"]

# (params, sample, t, text, control) -> (eps, attn_store)
UNetFn = Callable[..., Tuple[jax.Array, dict]]

# jitted official-mode programs, keyed on the statics their closures bake in
# (bounded FIFO — same discipline as inversion.py's program caches)
_OFFICIAL_EDIT_CACHE: dict = {}
_OFFICIAL_EDIT_CACHE_MAX = 4


def _controller_gates(ctx: Optional[ControlContext], i) -> dict:
    """Per-step controller edit activity, as fixed-shape scalars for the
    telemetry stream: the mean cross-replace gate at step ``i`` (the alpha
    that blends source maps into the edit streams) and whether the
    self/temporal replacement window covers the step. ``i`` may be traced."""
    if ctx is None:
        return {"cross_gate_mean": jnp.asarray(0.0, jnp.float32),
                "self_edit_active": jnp.asarray(0, jnp.int32)}
    lo, hi = ctx.self_replace_range
    return {
        "cross_gate_mean": jnp.mean(ctx.cross_replace_alpha[i]).astype(jnp.float32),
        "self_edit_active": jnp.logical_and(i >= lo, i < hi).astype(jnp.int32),
    }


def _mask_series_entry(maps_sum, blend_cfg, step_index, latent_hw):
    """The LocalBlend observability channels for one step: the mask the
    blend used (obs.attention's pooled resolution), its per-stream/frame
    coverage fraction, and whether the blend gate was open."""
    mask = blend_mask(maps_sum, blend_cfg, latent_hw).astype(jnp.float32)
    pooled = jax.image.resize(
        mask, mask.shape[:2] + ATTN_HEAT_RES, method="linear"
    )
    return {
        "mask_cov": mask.mean(axis=(2, 3)),
        "mask_heat": pooled,
        "blend_active": (step_index >= blend_cfg.start_blend).astype(jnp.int32),
    }


def _pack_step_outputs(telemetry, tel, attn_maps, attn, dev=None):
    """Scan ``ys`` for the optional observability channels (None when all
    are off, so the off-path scan is the exact pre-observability scan)."""
    ys = {}
    if telemetry:
        ys["tel"] = tel
    if dev is not None:
        ys["dev"] = dev
    if attn_maps:
        ys["attn"] = attn
    return ys or None


def make_unet_fn(model) -> UNetFn:
    """Adapter from a linen UNet module to the pipeline's callable contract.

    Quantized parameter trees (``models/quant.py`` :class:`QuantizedTensor`
    leaves, produced by ``convert.quantize_unet_params`` at load time) are
    dequantized INSIDE the traced fn to the model's compute dtype — the
    low-precision weights stay the compiled program's inputs (the
    bytes-accessed win) and the upcast happens at the matmul seam, the same
    convention as the float8 temporal-map capture. Unquantized trees pass
    through untouched, so the off path's program is byte-identical.

    ``deep_mode``/``deep_feature`` forward the DeepCache reuse seam to the
    model (see :meth:`UNet3DConditionModel.__call__`); the default
    ``"full"`` call is exactly the pre-reuse adapter.
    """
    from videop2p_tpu.models.quant import QuantizedTensor, dequantize_tree

    def fn(params, sample, t, text, control=None, *, deep_mode="full",
           deep_feature=None):
        # init() also returns sown collections (sow runs during init);
        # passing them back into apply would make sow append a second entry
        # per site — keep only the parameter collections.
        variables = {
            k: v for k, v in params.items() if k not in ("attn_store", "attn_base")
        }
        if any(isinstance(x, QuantizedTensor) for x in jax.tree_util.tree_leaves(
                variables, is_leaf=lambda x: isinstance(x, QuantizedTensor))):
            variables = dequantize_tree(variables, model.dtype)
        kwargs = ({} if deep_mode == "full"
                  else {"deep_mode": deep_mode, "deep_feature": deep_feature})
        out, store = model.apply(
            variables, sample, t, text, control,
            mutable=["attn_store", "attn_base"], **kwargs
        )
        return out, store

    return fn


def edit_sample(
    unet_fn: UNetFn,
    params,
    scheduler: DDIMScheduler,
    latents: jax.Array,
    cond_embeddings: jax.Array,
    uncond_embeddings: jax.Array,
    *,
    num_inference_steps: int = 50,
    guidance_scale: float = 7.5,
    ctx: Optional[ControlContext] = None,
    source_uses_cfg: bool = True,
    eta: float = 0.0,
    key: Optional[jax.Array] = None,
    dependent_sampler: Optional[DependentNoiseSampler] = None,
    blend_res: Optional[Tuple[int, int]] = None,
    null_uncond_embeddings: Optional[jax.Array] = None,
    cached_source: Optional[CachedSource] = None,
    step_positions=None,
    telemetry: bool = False,
    device_probe: Optional[Callable] = None,
    attn_maps: bool = False,
    reuse_schedule: Optional[str] = None,
    student_head: Optional[dict] = None,
) -> jax.Array:
    """Run the controlled denoise loop; returns final latents (P, F, h, w, C).

    ``latents``: x_T, shape (1, F, h, w, C) or (P, F, h, w, C) — a batch-1
    latent is expanded so source & edit share x_T (the reference's
    ``prepare_latents`` expansion, pipeline_tuneavideo.py:312-314).
    ``cond_embeddings``: (P, L, D) text embeddings, source prompt first.
    ``uncond_embeddings``: (L, D) or (1, L, D) — the raw encoder uncond used
    by every stream.
    ``null_uncond_embeddings``: optional per-step null-text optimization
    output, (num_steps, L, D) or (num_steps, 1, L, D) — injected into the
    SOURCE stream's uncond slot only each step; the edit streams keep the raw
    uncond (the reference's ``text_embeddings[0] = uncond_embeddings_pre[i]``,
    pipeline_tuneavideo.py:399-403).
    ``source_uses_cfg=False`` is the --fast mode source branch.
    ``cached_source``: cached-source fast mode — the source stream is dropped
    from the batch entirely; its latents replay the inversion trajectory
    exactly and the controllers read its attention maps from the capture
    (see :mod:`videop2p_tpu.pipelines.cached`). Requires
    ``source_uses_cfg=False``, ``eta=0`` and no null-text embeddings.

    ``step_positions``: the step-reduction seam (cached mode only). A
    strictly increasing sequence of ``num_inference_steps`` positions into
    the capture's base edit-step grid
    (:meth:`~videop2p_tpu.core.ddim.DDIMScheduler.subset_positions` is the
    canonical producer) — the edit then visits only those base timesteps
    from ONE base-steps inversion: the source replay reads the trajectory
    at the visited grid points (still exact — stream 0 stays the capture's
    x_0 bit-for-bit), the captured maps are indexed at the mapped base
    steps, and the scheduler walks the non-uniform grid via explicit
    ``prev_timestep``. The controller must be built for the SUBSET step
    count; gated subset steps must map inside the captured windows
    (``pipelines.cached.check_subset_windows`` — validated here when the
    controller is concrete, and by the serving layer before tracing).

    Per-frame ("multi") conditioning (pipeline_tuneavideo.py:366-367,399-402):
    pass ``cond_embeddings`` as (P, F, L, D); ``uncond_embeddings`` stays
    (L, D) and broadcasts per frame, and ``null_uncond_embeddings`` may be
    per-frame (num_steps, F, L, D).

    ``telemetry=True``: return ``(latents, tel)`` where ``tel`` stacks
    per-DDIM-step scalars riding the scan output (zero extra dispatches —
    obs.telemetry): post-step latent abs-max/mean + NaN/inf counts, the
    controller's cross-edit gate mean at that step, and whether the
    self/temporal replacement window was active. Off by default; the
    telemetry-off program is unchanged (tests/test_obs.py pins the outputs
    bit-exact, cached replay exactness included).

    ``device_probe``: a per-device telemetry probe for sharded runs
    (:func:`videop2p_tpu.obs.comm.make_device_probe`): called on the
    post-step latents inside the scan body, its fixed-shape output dict
    (per-device abs-max/mean/NaN/inf of each device's LOCAL shard plus a
    cross-replica divergence scalar) rides the scan ``ys`` — the same
    zero-extra-dispatch contract as ``telemetry``. Off (None) by default;
    the probe-off program is unchanged.

    ``attn_maps=True``: additionally return a per-step attention capture
    record riding the same scan (obs.attention — zero extra dispatches):
    pooled per-token cross-attention heatmaps over the conditional
    streams, per-site attention entropies, and (when a LocalBlend is
    configured) the blend-mask time series with coverage fractions. The
    return is ``latents`` plus the requested records in fixed order:
    ``(latents[, tel][, dev][, attn])``. Off by default — the capture-off
    program is byte-identical (tests/test_quality.py pins it).

    ``reuse_schedule``: cross-step deep-feature reuse (cached mode only;
    :mod:`videop2p_tpu.pipelines.reuse`). ``"uniform:K"`` /
    ``"custom:<p0,p1,...>"`` mark the steps that run the FULL UNet; on the
    remaining steps the deep down/mid/up stages are skipped and the cached
    deep feature — carried in the scan state — is reused via a
    ``lax.cond`` in the scan body, so the whole edit stays ONE compiled
    program. Incompatible with ``attn_maps`` (shallow steps produce no
    attention store). ``"off"``/None leaves the scan body byte-identical.

    ``student_head``: the consistency-distilled student's time-conditioning
    head (:func:`videop2p_tpu.train.distill.apply_time_head` params; cached
    mode only — the student rides the cached replay at 1–4 subset steps).
    When set, every edit-stream ε prediction is modulated by the head
    before CFG and the scheduler step; the source stream is REPLAYED from
    the capture regardless, so ``src_err == 0.0`` is structurally
    unaffected. ``None`` (the default) leaves the scan body byte-identical
    — the student-off program is the pre-distillation program.
    """
    P = cond_embeddings.shape[0]
    multi = cond_embeddings.ndim == 4
    # latents stay float32 in the scan carry; the UNet casts to its own
    # compute dtype internally (scheduler math is fp32 for step fidelity)
    latents = latents.astype(jnp.float32)
    if latents.shape[0] == 1 and P > 1:
        latents = jnp.broadcast_to(latents, (P,) + latents.shape[1:])
    elif latents.shape[0] != P:
        raise ValueError(f"latents batch {latents.shape[0]} != num prompts {P}")
    video_length = latents.shape[1]
    latent_hw = latents.shape[2:4]
    text_len = cond_embeddings.shape[-2]
    if multi and cond_embeddings.shape[1] != video_length:
        raise ValueError(
            f"per-frame cond_embeddings {cond_embeddings.shape} do not match "
            f"video_length {video_length}"
        )

    timesteps = jnp.asarray(scheduler.timesteps(num_inference_steps))
    if uncond_embeddings.ndim == 3 and uncond_embeddings.shape[0] == 1:
        uncond_embeddings = uncond_embeddings[0]
    if uncond_embeddings.ndim != 2:
        raise ValueError(
            f"uncond_embeddings must be (L, D) or (1, L, D), got "
            f"{uncond_embeddings.shape}; per-step null-text embeddings go in "
            "null_uncond_embeddings"
        )
    if multi:
        # per-frame conditioning: every stream's uncond broadcasts per frame
        # (the reference repeats embeddings '(b f) n c', :366-367)
        uncond_embeddings = jnp.broadcast_to(
            uncond_embeddings[None], (video_length,) + uncond_embeddings.shape
        )

    if step_positions is not None and cached_source is None:
        raise ValueError(
            "step_positions is the cached fast path's step-reduction seam — "
            "it requires cached_source"
        )
    if reuse_schedule not in (None, "off"):
        if cached_source is None:
            raise ValueError(
                "reuse_schedule is the cached fast path's deep-feature reuse "
                "seam — it requires cached_source"
            )
        if attn_maps:
            raise ValueError(
                "attn_maps capture reads every step's attention store and "
                "shallow reuse steps do not produce one — run attention "
                "capture with reuse_schedule='off'"
            )
    if student_head is not None and cached_source is None:
        raise ValueError(
            "student_head is the cached fast path's few-step student seam — "
            "it requires cached_source"
        )
    if cached_source is not None:
        if source_uses_cfg:
            raise ValueError("cached_source requires fast mode (source_uses_cfg=False)")
        if null_uncond_embeddings is not None:
            raise ValueError(
                "cached_source replays the source exactly — null-text "
                "embeddings have nothing left to correct and are not injected"
            )
        if eta > 0:
            raise ValueError(
                "cached_source requires eta=0: η-variance noise would make the "
                "live source stream stochastic while the cached replay is "
                "deterministic"
            )
        if step_positions is not None:
            from videop2p_tpu.pipelines.cached import validate_step_positions

            step_positions = validate_step_positions(
                step_positions, cached_source.num_steps
            )
            if len(step_positions) != num_inference_steps:
                raise ValueError(
                    f"step_positions has {len(step_positions)} entries, edit "
                    f"runs {num_inference_steps}"
                )
        elif cached_source.num_steps != num_inference_steps:
            raise ValueError(
                f"cached trajectory covers {cached_source.num_steps} steps, "
                f"edit runs {num_inference_steps} (pass step_positions for a "
                "timestep-subset fast path from one inversion)"
            )
        return _edit_sample_cached(
            unet_fn, params, scheduler, latents, cond_embeddings,
            uncond_embeddings, cached_source,
            num_inference_steps=num_inference_steps,
            guidance_scale=guidance_scale, ctx=ctx,
            blend_res=blend_res, step_positions=step_positions,
            telemetry=telemetry,
            device_probe=device_probe, attn_maps=attn_maps,
            reuse_schedule=reuse_schedule,
            student_head=student_head,
        )

    # the source stream's per-step uncond: the null-text sequence when given,
    # else the raw uncond every step
    if null_uncond_embeddings is not None:
        if null_uncond_embeddings.ndim == 4 and null_uncond_embeddings.shape[1] == 1:
            # (steps, 1, L, D) — the batch-1 source-stream optimization output
            null_uncond_embeddings = null_uncond_embeddings[:, 0]
        if not multi and null_uncond_embeddings.ndim == 4:
            raise ValueError(
                "null-text embeddings must be optimized on the batch-1 "
                f"source stream, got shape {null_uncond_embeddings.shape}"
            )
        if multi and null_uncond_embeddings.ndim == 3:
            # one (L, D) per step → broadcast over frames (the reference's
            # multi injection fills all F slots, :399-402)
            null_uncond_embeddings = jnp.broadcast_to(
                null_uncond_embeddings[:, None],
                (null_uncond_embeddings.shape[0], video_length)
                + null_uncond_embeddings.shape[1:],
            )
        expected = (num_inference_steps,) + uncond_embeddings.shape
        if null_uncond_embeddings.shape != expected:
            raise ValueError(
                f"null-text embeddings must have shape {expected}, "
                f"got {null_uncond_embeddings.shape}"
            )
        uncond0_seq = null_uncond_embeddings
    else:
        uncond0_seq = jnp.broadcast_to(
            uncond_embeddings[None], (num_inference_steps,) + uncond_embeddings.shape
        )

    if key is None:
        key = jax.random.key(0)
    use_blend = ctx is not None and ctx.blend is not None

    # fast mode (source_uses_cfg=False) discards the source stream's uncond
    # prediction (the reference computes then overwrites it,
    # pipeline_tuneavideo.py:412-415) — skip that forward entirely: the CFG
    # batch shrinks from 2P to (P−1)+P streams, a ~25 % FLOP cut at P=2.
    U = P if source_uses_cfg else P - 1

    def step_text(uncond0):
        # stream 0's uncond is per-step (null-text seam); edit streams keep
        # the raw uncond (pipeline_tuneavideo.py:399-403). In fast mode the
        # source uncond stream does not exist (its output was unused).
        u = jnp.broadcast_to(uncond_embeddings[None], (P,) + uncond_embeddings.shape)
        if source_uses_cfg:
            u = jnp.concatenate([uncond0[None], u[1:]], axis=0)
        else:
            u = u[1:]
        return jnp.concatenate([u, cond_embeddings], axis=0)

    def step_latents(latents):
        return jnp.concatenate([latents[P - U:], latents], axis=0)

    maps_sum = None
    if use_blend:
        # fixed carry shape: count blend sites from an abstract forward
        control0 = AttnControl(ctx=ctx, step_index=jnp.asarray(0), num_uncond=U)
        _, store_shape = jax.eval_shape(
            unet_fn,
            params,
            step_latents(latents),
            timesteps[0],
            step_text(uncond0_seq[0]),
            control0,
        )
        maps_shape = jax.eval_shape(
            lambda s: blend_maps_from_store(
                s,
                latent_hw=latent_hw,
                video_length=video_length,
                num_prompts=P,
                text_len=text_len,
                blend_res=blend_res,
                num_uncond=U,
            ),
            store_shape,
        )
        maps_sum = jnp.zeros(maps_shape.shape, maps_shape.dtype)

    def body(carry, xs):
        latents, maps_sum, key = carry
        t, i, uncond = xs
        latent_in = step_latents(latents)
        text = step_text(uncond)
        control = (
            AttnControl(ctx=ctx, step_index=i, num_uncond=U) if ctx is not None else None
        )
        eps_all, store = unet_fn(params, latent_in, t, text, control)
        eps_uncond, eps_text = eps_all[:U], eps_all[U:]
        if source_uses_cfg:
            eps = eps_uncond + guidance_scale * (eps_text - eps_uncond)
        else:
            # edit streams get CFG against their own uncond; the source
            # stream replays its cond-only prediction exactly
            eps_edit = eps_uncond + guidance_scale * (eps_text[1:] - eps_uncond)
            eps = jnp.concatenate([eps_text[:1], eps_edit], axis=0)

        key, sub = jax.random.split(key)
        variance_noise = None
        if eta > 0:
            if dependent_sampler is not None:
                variance_noise = dependent_sampler.sample_like(sub, eps)
            else:
                variance_noise = jax.random.normal(sub, eps.shape, eps.dtype)

        latents, _ = scheduler.step(
            eps, t, latents, num_inference_steps, eta=eta, variance_noise=variance_noise
        )

        if use_blend:
            maps_sum = maps_sum + blend_maps_from_store(
                store,
                latent_hw=latent_hw,
                video_length=video_length,
                num_prompts=P,
                text_len=text_len,
                blend_res=blend_res,
                num_uncond=U,
            )
            latents = local_blend(latents, maps_sum, ctx.blend, i)
        if ctx is not None and ctx.spatial_replace_until > 0:
            # SpatialReplace step callback (run_videop2p.py:237-241): inject
            # the source latents into every edit stream while active
            active = i < ctx.spatial_replace_until
            latents = jnp.where(
                active, jnp.broadcast_to(latents[:1], latents.shape), latents
            )
        tel = attn = dev = None
        if telemetry:
            tel = dict(latent_stats(latents), **_controller_gates(ctx, i))
        if device_probe is not None:
            dev = device_probe(latents)
        if attn_maps:
            attn = attn_step_record(
                store, num_uncond=U, num_cond=P, video_length=video_length,
                text_len=text_len, latent_hw=latent_hw,
            )
            if use_blend:
                attn.update(_mask_series_entry(maps_sum, ctx.blend, i, latent_hw))
        ys = _pack_step_outputs(telemetry, tel, attn_maps, attn, dev)
        return (latents, maps_sum, key), ys

    xs = (timesteps, jnp.arange(num_inference_steps), uncond0_seq)
    (latents, _, _), ys = jax.lax.scan(body, (latents, maps_sum, key), xs)
    out = (latents,)
    if telemetry:
        out += (ys["tel"],)
    if device_probe is not None:
        out += (ys["dev"],)
    if attn_maps:
        out += (ys["attn"],)
    return out if len(out) > 1 else latents


def _edit_sample_cached(
    unet_fn: UNetFn,
    params,
    scheduler: DDIMScheduler,
    latents: jax.Array,
    cond_embeddings: jax.Array,
    uncond_embeddings: jax.Array,
    cached: CachedSource,
    *,
    num_inference_steps: int,
    guidance_scale: float,
    ctx: Optional[ControlContext],
    blend_res: Optional[Tuple[int, int]],
    step_positions=None,
    telemetry: bool = False,
    device_probe: Optional[Callable] = None,
    attn_maps: bool = False,
    reuse_schedule: Optional[str] = None,
    student_head: Optional[dict] = None,
) -> jax.Array:
    """The cached-source denoise loop: only the P−1 edit streams run the
    UNet; the source stream is read off the reversed inversion trajectory
    (exact replay) and its controller inputs come from the capture
    (:mod:`videop2p_tpu.pipelines.cached`). Fully deterministic — the
    ``eta=0`` requirement means no randomness enters the loop.

    Inputs arrive normalized by :func:`edit_sample` (latents broadcast to
    (P, F, h, w, C), uncond as (L, D) — or per-frame in multi mode);
    ``step_positions`` (already validated) selects a timestep subset of the
    capture's base grid — the few-step fast path from one inversion.
    """
    import numpy as np

    P = cond_embeddings.shape[0]
    E = P - 1  # edit streams
    U = E  # their uncond streams
    if E < 1:
        raise ValueError("cached_source needs at least one edit prompt")
    video_length = latents.shape[1]
    latent_hw = latents.shape[2:4]
    text_len = cond_embeddings.shape[-2]
    subset = step_positions is not None
    if subset:
        base_steps = cached.num_steps
        positions = np.asarray(step_positions, dtype=np.int64)
        base_ts = np.asarray(scheduler.timesteps(base_steps))
        ts_np = base_ts[positions]
        ratio = scheduler.num_train_timesteps // base_steps
        # step j lands on the next subset timestep; the last step lands on
        # the base walk's own terminal target (< 0 → final ᾱ), so every
        # subset walk ends at the same "clean" state as the base walk
        prev_ts_np = np.concatenate([ts_np[1:], [base_ts[-1] - ratio]])
        timesteps = jnp.asarray(ts_np)
        # gate-coverage validation needs a CONCRETE controller; under a
        # trace (the serving programs pass ctx as a jit argument) the
        # caller validates before tracing (serve/programs.py does)
        if ctx is not None and not isinstance(
            ctx.cross_replace_alpha, jax.core.Tracer
        ):
            from videop2p_tpu.pipelines.cached import check_subset_windows

            check_subset_windows(ctx, cached, positions, num_inference_steps)
    else:
        timesteps = jnp.asarray(scheduler.timesteps(num_inference_steps))

    edit_latents = latents[1:]  # (E, F, h, w, C), fp32 from the caller
    cond_edit = cond_embeddings[1:]
    text = jnp.concatenate(
        [jnp.broadcast_to(uncond_embeddings[None], (E,) + uncond_embeddings.shape), cond_edit],
        axis=0,
    )

    if ctx is not None and ctx.kind != "empty":
        # a non-empty gate window with no captured maps would silently skip
        # the edit at every site of that type — fail loudly instead
        lo, hi = cached.self_window
        if cached.cross_len > 0 and not cached.cross_maps:
            raise ValueError(
                f"capture declares a {cached.cross_len}-step cross window but "
                "has no cross maps"
            )
        if hi > lo and not cached.temporal_maps:
            raise ValueError(
                f"capture declares self window {cached.self_window} but has "
                "no temporal maps"
            )

    use_blend = ctx is not None and ctx.blend is not None
    if use_blend and cached.blend_seq is None:
        raise ValueError(
            "LocalBlend is configured but the capture has no blend_seq — run "
            "ddim_inversion_captured(capture_blend=True)"
        )
    # src_seq[i] = source latent AFTER edit step i (= trajectory[N−i−1]);
    # a subset walk's step j lands on the NEXT visited grid point, and its
    # last step lands on x_0 — the replay reads exact trajectory values
    # either way
    if subset:
        positions_next = np.append(positions[1:], base_steps)
        src_seq = cached.src_latents[jnp.asarray(positions_next)]
    else:
        src_seq = cached.src_latents[1:]

    maps_sum = None
    if use_blend:
        control0 = AttnControl(
            ctx=ctx, step_index=jnp.asarray(0), num_uncond=U,
            cached_base=cached.base_tree_at(jnp.asarray(0)),
            cached_source=True,
        )
        _, store_shape = jax.eval_shape(
            unet_fn,
            params,
            jnp.concatenate([edit_latents, edit_latents], axis=0),
            timesteps[0],
            text,
            control0,
        )
        edit_maps_shape = jax.eval_shape(
            lambda s: blend_maps_from_store(
                s,
                latent_hw=latent_hw,
                video_length=video_length,
                num_prompts=E,
                text_len=text_len,
                blend_res=blend_res,
                num_uncond=U,
            ),
            store_shape,
        )
        maps_sum = jnp.zeros(
            (1 + E,) + edit_maps_shape.shape[1:], edit_maps_shape.dtype
        )

    # cross-step deep-feature reuse (pipelines/reuse.py): the schedule is a
    # STATIC per-step boolean riding xs; the deep feature (the final up
    # block's input) and the last full step's blend maps ride the carry, so
    # the edit stays ONE compiled program regardless of K
    reuse_full = None
    if reuse_schedule not in (None, "off"):
        from videop2p_tpu.pipelines.reuse import parse_reuse_schedule

        reuse_full = parse_reuse_schedule(reuse_schedule, num_inference_steps)
        if attn_maps:
            raise ValueError(
                "attn_maps capture is incompatible with reuse_schedule — "
                "shallow steps produce no attention store"
            )
    deep0 = last_maps0 = None
    if reuse_full is not None:
        reuse_control0 = (
            AttnControl(
                ctx=ctx, step_index=jnp.asarray(0), num_uncond=U,
                cached_base=cached.base_tree_at(jnp.asarray(0)),
                cached_source=True,
            )
            if ctx is not None
            else None
        )
        (_, deep_shape), _ = jax.eval_shape(
            lambda p, x: unet_fn(
                p, x, timesteps[0], text, reuse_control0, deep_mode="capture"
            ),
            params,
            jnp.concatenate([edit_latents, edit_latents], axis=0),
        )
        deep0 = jnp.zeros(deep_shape.shape, deep_shape.dtype)
        last_maps0 = (
            jnp.zeros(edit_maps_shape.shape, edit_maps_shape.dtype)
            if use_blend else jnp.zeros((0,), jnp.float32)
        )

    def body(carry, xs):
        if reuse_full is not None:
            edit_latents, maps_sum, deep_feat, last_maps = carry
            *xs, is_full = xs
        else:
            edit_latents, maps_sum = carry
        if subset:
            # base_i indexes the captured maps at the mapped base step; the
            # controller's own gates stay in subset-step space (i)
            t, i, src_after, blend_src, base_i, prev_t = xs
        else:
            t, i, src_after, blend_src = xs
            base_i, prev_t = i, None
        latent_in = jnp.concatenate([edit_latents, edit_latents], axis=0)
        control = (
            AttnControl(
                ctx=ctx, step_index=i, num_uncond=U,
                cached_base=cached.base_tree_at(base_i),
                cached_source=True,
            )
            if ctx is not None
            else None
        )
        if reuse_full is None:
            eps_all, store = unet_fn(params, latent_in, t, text, control)
        else:
            # both branches trace once; one executes per step. The sown
            # attention store must NOT cross the cond boundary (the shallow
            # branch has no deep attention sites, so the pytrees differ) —
            # the blend maps are reduced from it INSIDE the full branch and
            # only the fixed-shape reduction crosses.
            def _cond_maps(store):
                if not use_blend:
                    return jnp.zeros((0,), jnp.float32)
                return blend_maps_from_store(
                    store,
                    latent_hw=latent_hw,
                    video_length=video_length,
                    num_prompts=E,
                    text_len=text_len,
                    blend_res=blend_res,
                    num_uncond=U,
                )

            def _full_step(latent_in, deep_feat, last_maps):
                (eps, deep), store = unet_fn(
                    params, latent_in, t, text, control, deep_mode="capture"
                )
                return (
                    eps,
                    deep.astype(deep_feat.dtype),
                    _cond_maps(store).astype(last_maps.dtype),
                )

            def _shallow_step(latent_in, deep_feat, last_maps):
                eps, _ = unet_fn(
                    params, latent_in, t, text, control,
                    deep_mode="shallow", deep_feature=deep_feat,
                )
                return eps, deep_feat, last_maps

            eps_all, deep_feat, reuse_maps = jax.lax.cond(
                is_full, _full_step, _shallow_step,
                latent_in, deep_feat, last_maps,
            )
            last_maps = reuse_maps
        if student_head is not None:
            # the few-step student: the distilled time-conditioning head
            # modulates ε before CFG (train/distill.py). Only the edit
            # streams run the UNet here — the source stream is replayed
            # from the capture below, so src_err == 0.0 is untouched.
            from videop2p_tpu.train.distill import apply_time_head

            eps_all = apply_time_head(student_head, eps_all, t)
        eps_uncond, eps_text = eps_all[:E], eps_all[E:]
        eps = eps_uncond + guidance_scale * (eps_text - eps_uncond)
        edit_latents, _ = scheduler.step(
            eps, t, edit_latents, num_inference_steps, eta=0.0,
            variance_noise=None, prev_timestep=prev_t,
        )

        if use_blend:
            if reuse_full is None:
                edit_maps = blend_maps_from_store(
                    store,
                    latent_hw=latent_hw,
                    video_length=video_length,
                    num_prompts=E,
                    text_len=text_len,
                    blend_res=blend_res,
                    num_uncond=U,
                )
            else:
                # shallow steps re-add the LAST full step's edit maps — the
                # same "adjacent steps are nearly identical" premise the
                # deep-feature reuse itself rests on
                edit_maps = reuse_maps
            maps_sum = maps_sum + jnp.concatenate([blend_src, edit_maps], axis=0)
            full = jnp.concatenate([src_after, edit_latents], axis=0)
            full = local_blend(full, maps_sum, ctx.blend, i)
            edit_latents = full[1:]
        if ctx is not None and ctx.spatial_replace_until > 0:
            active = i < ctx.spatial_replace_until
            edit_latents = jnp.where(
                active,
                jnp.broadcast_to(src_after, edit_latents.shape),
                edit_latents,
            )
        tel = attn = dev = None
        if telemetry:
            # stats cover the EDIT streams only — the source stream is a
            # replayed constant here, by construction finite and exact
            tel = dict(latent_stats(edit_latents), **_controller_gates(ctx, i))
        if device_probe is not None:
            dev = device_probe(edit_latents)
        if attn_maps:
            # heat covers the E edit streams (the source stream is not in
            # the batch — its maps live in the inversion capture record);
            # the mask series keeps all 1+E streams, source first
            attn = attn_step_record(
                store, num_uncond=U, num_cond=E, video_length=video_length,
                text_len=text_len, latent_hw=latent_hw,
            )
            if use_blend:
                attn.update(_mask_series_entry(maps_sum, ctx.blend, i, latent_hw))
        ys = _pack_step_outputs(telemetry, tel, attn_maps, attn, dev)
        if reuse_full is not None:
            return (edit_latents, maps_sum, deep_feat, last_maps), ys
        return (edit_latents, maps_sum), ys

    if cached.blend_seq is None:
        blend_xs = jnp.zeros((num_inference_steps, 0))
    elif subset:
        # the source's blend contribution captured AT each visited step;
        # the mask's running sum covers fewer steps but is max-normalized
        blend_xs = cached.blend_seq[jnp.asarray(positions)]
    else:
        blend_xs = cached.blend_seq
    xs = (timesteps, jnp.arange(num_inference_steps), src_seq, blend_xs)
    if subset:
        xs += (jnp.asarray(positions, jnp.int32), jnp.asarray(prev_ts_np))
    if reuse_full is not None:
        xs += (jnp.asarray(reuse_full),)
        carry0 = (edit_latents, maps_sum, deep0, last_maps0)
    else:
        carry0 = (edit_latents, maps_sum)
    final_carry, ys = jax.lax.scan(body, carry0, xs)
    edit_latents = final_carry[0]
    # stream 0 = the exact inversion reconstruction (trajectory[0] = x_0)
    out = jnp.concatenate([cached.src_latents[-1], edit_latents], axis=0)
    outs = (out,)
    if telemetry:
        outs += (ys["tel"],)
    if device_probe is not None:
        outs += (ys["dev"],)
    if attn_maps:
        outs += (ys["attn"],)
    return outs if len(outs) > 1 else out


def official_edit(
    unet_fn: UNetFn,
    params,
    scheduler: DDIMScheduler,
    trajectory: jax.Array,
    cond_embeddings: jax.Array,
    uncond_embedding: jax.Array,
    *,
    num_inference_steps: int = 50,
    guidance_scale: float = 7.5,
    ctx: Optional[ControlContext] = None,
    num_inner_steps: int = 10,
    epsilon: float = 1e-5,
    null_text_precision: str = "fp32",
    null_text_mode: str = "optimize",
    hybrid_inner_steps: int = 3,
    early_stop: bool = True,
    dependent_weight: float = 0.0,
    dependent_sampler: Optional[DependentNoiseSampler] = None,
    eta: float = 0.0,
    key: Optional[jax.Array] = None,
    blend_res: Optional[Tuple[int, int]] = None,
    donate: bool = True,
    return_null_stats: bool = False,
):
    """The full official mode — null-text optimization plus the controlled
    full-CFG edit — as ONE jitted device program.

    The split flow surfaces the optimized uncond trajectory
    (num_steps, 1, L, D) on the host between phases: a device→host→device
    round trip plus a second program dispatch, each riding the tunnel. Here
    :func:`edit_sample` consumes the optimized sequence straight out of the
    null-text scan — the embeddings never materialize outside the program,
    and the trajectory buffer is donated to it (``donate=False`` if the
    caller still needs it). HBM note: this holds the null-text grad program
    and the CFG edit program in ONE executable — at fp32 SD scale that can
    exceed a 16 GB chip (the CLI's phase-split + ``jax.clear_caches()``
    exists for that reason); the bf16/``mixed`` working points fit.

    ``trajectory``: (num_steps+1, B=1, F, h, w, C) from
    :func:`~videop2p_tpu.pipelines.inversion.ddim_inversion`;
    ``cond_embeddings``: (P, L, D), source prompt first;
    ``uncond_embedding``: (L, D) or (1, L, D).

    Returns final latents (P, F, h, w, C); with ``return_null_stats=True``
    returns ``(latents, stats)`` — the fused null-text program's
    ``{"final_loss", "inner_steps"}`` record.

    ``null_text_mode``/``hybrid_inner_steps`` select the amortized
    (closed-form negative-prompt) or hybrid (joint K-step) null-text
    substitutes (pipelines/inversion.py) inside the same single program —
    the ≥3× cheaper official path the quality rules gate.
    """
    # lazy import: inversion.py imports this module for the UNetFn contract
    from videop2p_tpu.pipelines.inversion import null_text_optimization

    if uncond_embedding.ndim == 3 and uncond_embedding.shape[0] == 1:
        uncond_embedding = uncond_embedding[0]
    if uncond_embedding.ndim != 2:
        raise ValueError(
            f"uncond_embedding must be (L, D) or (1, L, D), got "
            f"{uncond_embedding.shape}"
        )
    if key is None:
        key = jax.random.key(0)
    # CPU cannot alias donated buffers — avoid the per-call warning
    donate = donate and jax.default_backend() != "cpu"

    cache_key = (
        unet_fn, id(scheduler), id(dependent_sampler), id(ctx),
        float(guidance_scale), int(num_inner_steps), int(num_inference_steps),
        float(dependent_weight), float(epsilon), float(eta),
        bool(early_stop), null_text_precision, null_text_mode,
        int(hybrid_inner_steps), blend_res, bool(donate),
    )
    program = _OFFICIAL_EDIT_CACHE.get(cache_key)
    if program is None:

        def program_fn(p, cond, uncond, traj, k):
            k_null, k_edit = jax.random.split(k)
            null_seq, losses, inner_taken = null_text_optimization(
                unet_fn, p, scheduler, traj, cond[:1], uncond[None],
                num_inference_steps=num_inference_steps,
                guidance_scale=guidance_scale,
                num_inner_steps=num_inner_steps,
                epsilon=epsilon,
                null_text_precision=null_text_precision,
                null_text_mode=null_text_mode,
                hybrid_inner_steps=hybrid_inner_steps,
                dependent_weight=dependent_weight,
                dependent_sampler=dependent_sampler,
                key=k_null,
                early_stop=early_stop,
                return_losses=True,
                return_inner_steps=True,
            )
            out = edit_sample(
                unet_fn, p, scheduler, traj[-1], cond, uncond,
                num_inference_steps=num_inference_steps,
                guidance_scale=guidance_scale,
                ctx=ctx,
                source_uses_cfg=True,
                eta=eta,
                key=k_edit,
                dependent_sampler=dependent_sampler if eta > 0 else None,
                blend_res=blend_res,
                null_uncond_embeddings=null_seq,
            )
            return out, losses, inner_taken

        program = jax.jit(
            program_fn, donate_argnums=(3,) if donate else ()
        )
        while len(_OFFICIAL_EDIT_CACHE) >= _OFFICIAL_EDIT_CACHE_MAX:
            _OFFICIAL_EDIT_CACHE.pop(next(iter(_OFFICIAL_EDIT_CACHE)))
        _OFFICIAL_EDIT_CACHE[cache_key] = program

    out, losses, inner_taken = program(
        params, cond_embeddings, uncond_embedding, trajectory, key
    )
    if return_null_stats:
        return out, {"final_loss": losses, "inner_steps": inner_taken}
    return out
