"""DDIM inversion and null-text inversion.

TPU-native re-design of the reference ``NullInversion``
(/root/reference/run_videop2p.py:443-648) and the Stage-1 validation inversion
(/root/reference/tuneavideo/util.py:52-92):

  * ``ddim_inversion`` — 50 forward-DDIM steps, conditional-only (guidance 1),
    as a ``lax.scan`` that keeps the full latent trajectory
    (run_videop2p.py:558-578). The fork's dependent-noise blend
    ``(1-w)·ε̂ + w·ar_noise`` (run_videop2p.py:465-471) is key-threaded.
  * ``null_text_optimization`` — per-step optimization of the unconditional
    embedding (run_videop2p.py:580-612): outer scan over the 50 steps, inner
    ``lax.while_loop`` Adam with the reference's decayed lr
    ``1e-2·(1−i/100)``, ≤``num_inner_steps`` iterations and early stop at
    ``loss < ε + i·2e-5`` — the early stop becomes the while condition, so
    shapes stay static under jit.

The reference's Python-loop-with-break structure is the hard functionalization
case SURVEY §7 ranks #3; the while_loop preserves its exact update-then-check
semantics (loss is measured pre-update, the update it gated is still applied).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from videop2p_tpu.core.ddim import DDIMScheduler
from videop2p_tpu.core.noise import DependentNoiseSampler
from videop2p_tpu.models.attention import AttnControl
from videop2p_tpu.pipelines.cached import CachedSource, filter_site_tree
from videop2p_tpu.pipelines.sampling import UNetFn
from videop2p_tpu.pipelines.stores import blend_maps_from_store

__all__ = ["ddim_inversion", "ddim_inversion_captured", "null_text_optimization"]

# jitted chunk scans for the outer_chunk path, keyed by the statics their
# closures bake in (runtime arrays enter as jit inputs); bounded FIFO
_CHUNK_SCAN_CACHE: dict = {}
_CHUNK_SCAN_CACHE_MAX = 4


def ddim_inversion(
    unet_fn: UNetFn,
    params,
    scheduler: DDIMScheduler,
    latents: jax.Array,
    cond_embedding: jax.Array,
    *,
    num_inference_steps: int = 50,
    dependent_weight: float = 0.0,
    dependent_sampler: Optional[DependentNoiseSampler] = None,
    key: Optional[jax.Array] = None,
    return_eps: bool = False,
):
    """Invert clean latents x_0 to noise x_T.

    ``latents``: (B, F, h, w, C) clean (VAE-encoded, scaled) latents;
    ``cond_embedding``: (B, L, D) source-prompt embedding (no CFG — the
    reference inverts with guidance 1, run_videop2p.py:558-572).

    Returns the full trajectory (num_steps+1, B, F, h, w, C) with
    ``[0] = x_0`` and ``[-1] = x_T`` (the reference's ``all_latent`` list).
    ``dependent_weight > 0`` blends the model output with AR noise:
    ``ε = (1-w)·ε̂ + w·ar_noise`` (run_videop2p.py:467-471).

    ``return_eps``: also return the per-step model outputs
    (num_steps, B, F, h, w, C), ordered along the inversion walk. DDIM's
    ``next_step``/``prev_step`` are linear in (x, ε) with identical
    coefficients, so ``prev_step(eps[i], t[i], trajectory[i+1])`` recovers
    ``trajectory[i]`` EXACTLY — a cached-ε backward replay of the source
    stream is exact where the reference's fast mode re-predicts ε from the
    drifting latent (pipeline_tuneavideo.py:412-415) and only approximately
    reconstructs. This is the seam for replaying the source stream without
    re-running its forwards (tests/test_pipelines.py pins the property).
    """
    # latents stay float32 through the walk regardless of the UNet's compute
    # dtype — scheduler math is fp32 (the reference keeps the Stage-2 UNet and
    # latents fp32 for inversion fidelity, run_videop2p.py:111-113)
    latents = latents.astype(jnp.float32)
    # ascending timesteps: the reference walks timesteps[-(i+1)] for i in 0..N
    # (run_videop2p.py:563-566)
    timesteps = jnp.asarray(scheduler.timesteps(num_inference_steps)[::-1].copy())
    if key is None:
        key = jax.random.key(0)

    def body(carry, t):
        latent, key = carry
        eps, _ = unet_fn(params, latent, t, cond_embedding, None)
        if dependent_weight > 0.0:
            if dependent_sampler is None:
                raise ValueError("dependent_weight > 0 requires dependent_sampler")
            key, sub = jax.random.split(key)
            ar_noise = dependent_sampler.sample_like(sub, eps)
            eps = (1.0 - dependent_weight) * eps + dependent_weight * ar_noise
        latent = scheduler.next_step(eps, t, latent, num_inference_steps)
        # return_eps is static: without it the scan must not stack a dead
        # trajectory-sized ε buffer (eager callers get no DCE)
        ys = (latent, eps.astype(jnp.float32)) if return_eps else latent
        return (latent, key), ys

    (_, _), ys = jax.lax.scan(body, (latents, key), timesteps)
    trajectory, eps_seq = ys if return_eps else (ys, None)
    full = jnp.concatenate([latents[None], trajectory], axis=0)
    if return_eps:
        return full, eps_seq
    return full


def ddim_inversion_captured(
    unet_fn: UNetFn,
    params,
    scheduler: DDIMScheduler,
    latents: jax.Array,
    cond_embedding: jax.Array,
    *,
    num_inference_steps: int = 50,
    cross_len: int = 0,
    self_window: Tuple[int, int] = (0, 0),
    capture_blend: bool = False,
    blend_res: Optional[Tuple[int, int]] = None,
    dependent_weight: float = 0.0,
    dependent_sampler: Optional[DependentNoiseSampler] = None,
    key: Optional[jax.Array] = None,
    temporal_maps_dtype=None,
) -> Tuple[jax.Array, CachedSource]:
    """DDIM inversion that also captures everything a cached-source edit
    needs (see :mod:`videop2p_tpu.pipelines.cached` for the design).

    ``temporal_maps_dtype``: optional narrower STORAGE dtype for the
    captured temporal (attn_temp) probability maps — e.g.
    ``jnp.float8_e4m3fn``. The temporal tree is the long-video memory
    cliff: per spatial position it holds an F×F map, so its bytes grow
    quadratically with frame count (8f: 0.6 GiB → 24f: 5.8 GiB at SD
    scale) while everything else grows linearly. Probabilities live in
    [0, 1] where e4m3 keeps ~2 significant digits; the maps are read back
    upcast to the compute dtype (cached.py ``base_tree_at``), they only
    feed the EDIT stream's map replacement, and the source-stream replay
    is ε-based — its bit-exactness guarantee is unaffected
    (tests/test_cached.py pins both properties).

    Same walk as :func:`ddim_inversion`, but split into segments so that the
    full per-head controlled-site probabilities are stacked ONLY for the
    inversion steps whose maps the edit's gates will actually read:

      * cross maps for edit steps [0, ``cross_len``) — inversion steps
        [N−cross_len, N);
      * temporal maps for edit steps [lo, hi) = ``self_window`` — inversion
        steps [N−hi, N−lo);
      * per-step LocalBlend store contributions for every step when
        ``capture_blend`` (head-meaned and blend-site-stacked first — tiny).

    Edit step *i* reads the maps captured at inversion step ``N−1−i``: the
    same timestep, with the latent one trajectory position earlier than a
    live source stream would use (the disclosed approximation; the latent
    replay itself is exact). Returns ``(trajectory, CachedSource)``.
    """
    if dependent_weight > 0.0 and dependent_sampler is None:
        raise ValueError("dependent_weight > 0 requires dependent_sampler")
    N = num_inference_steps
    lo, hi = self_window
    if not (0 <= lo <= hi <= N):
        raise ValueError(f"self_window {self_window} outside [0, {N}]")
    if not (0 <= cross_len <= N):
        raise ValueError(f"cross_len {cross_len} outside [0, {N}]")
    latents = latents.astype(jnp.float32)
    video_length = latents.shape[1]
    latent_hw = latents.shape[2:4]
    text_len = cond_embedding.shape[-2]
    timesteps = jnp.asarray(scheduler.timesteps(N)[::-1].copy())
    if key is None:
        key = jax.random.key(0)

    def run_segment(latent, key, ts, want_cross, want_temporal):
        capture = want_cross or want_temporal

        def body(carry, t):
            latent, key = carry
            control = (
                AttnControl(ctx=None, step_index=jnp.asarray(0, jnp.int32), capture=True)
                if capture
                else None
            )
            eps, store = unet_fn(params, latent, t, cond_embedding, control)
            if dependent_weight > 0.0:
                key, sub = jax.random.split(key)
                ar_noise = dependent_sampler.sample_like(sub, eps)
                eps = (1.0 - dependent_weight) * eps + dependent_weight * ar_noise
            latent = scheduler.next_step(eps, t, latent, N)
            ys = {"latent": latent}
            if capture_blend:
                ys["blend"] = blend_maps_from_store(
                    store,
                    latent_hw=latent_hw,
                    video_length=video_length,
                    num_prompts=1,
                    text_len=text_len,
                    blend_res=blend_res,
                    num_uncond=0,
                )
            if want_cross:
                ys["cross"] = filter_site_tree(store["attn_base"], "attn2")
            if want_temporal:
                t_tree = filter_site_tree(store["attn_base"], "attn_temp")
                if temporal_maps_dtype is not None:
                    t_tree = jax.tree.map(
                        lambda a: a.astype(temporal_maps_dtype), t_tree
                    )
                ys["temporal"] = t_tree
            return (latent, key), ys

        return jax.lax.scan(body, (latent, key), ts)

    # segment the walk at the capture-window edges (inversion-step space):
    # cross maps live in [N−cross_len, N), temporal in [N−hi, N−lo)
    bounds = sorted({0, N - hi, N - lo, N - cross_len, N})
    carry = (latents, key)
    lat_pieces, blend_pieces, cross_pieces, temporal_pieces = [], [], [], []
    for s, e in zip(bounds[:-1], bounds[1:]):
        want_cross = s >= N - cross_len
        want_temporal = s >= N - hi and e <= N - lo
        carry, ys = run_segment(*carry, timesteps[s:e], want_cross, want_temporal)
        lat_pieces.append(ys["latent"])
        if capture_blend:
            blend_pieces.append(ys["blend"])
        if want_cross:
            cross_pieces.append(ys["cross"])
        if want_temporal:
            temporal_pieces.append(ys["temporal"])

    trajectory = jnp.concatenate([latents[None]] + lat_pieces, axis=0)

    def stack_reversed(pieces):
        # inversion order → edit order (edit step i ↔ inversion step N−1−i)
        if not pieces:
            return None
        return jax.tree.map(lambda *xs: jnp.flip(jnp.concatenate(xs, axis=0), axis=0), *pieces)

    cached = CachedSource(
        src_latents=jnp.flip(trajectory, axis=0),
        cross_maps=stack_reversed(cross_pieces),
        temporal_maps=stack_reversed(temporal_pieces),
        blend_seq=stack_reversed(blend_pieces) if capture_blend else None,
        cross_len=cross_len,
        self_window=(lo, hi),
    )
    return trajectory, cached


def null_text_optimization(
    unet_fn: UNetFn,
    params,
    scheduler: DDIMScheduler,
    trajectory: jax.Array,
    cond_embedding: jax.Array,
    uncond_embedding: jax.Array,
    *,
    num_inference_steps: int = 50,
    guidance_scale: float = 7.5,
    num_inner_steps: int = 10,
    epsilon: float = 1e-5,
    dependent_weight: float = 0.0,
    dependent_sampler: Optional[DependentNoiseSampler] = None,
    key: Optional[jax.Array] = None,
    outer_chunk: Optional[int] = None,
    early_stop: bool = True,
    return_losses: bool = False,
) -> jax.Array:
    """Optimize a per-step unconditional embedding that makes CFG denoising
    replay the recorded inversion trajectory (run_videop2p.py:580-612).

    ``early_stop=False`` runs exactly ``num_inner_steps`` inner iterations
    per outer step (no ``loss < ε + i·2e-5`` break): the work becomes
    weight-independent, giving a stable wall-clock for benchmarking — the
    reference-faithful early-stopped run varies 157–418 s with the random
    early-stop point (run_videop2p.py:603).

    ``trajectory``: (num_steps+1, B, F, h, w, C) from :func:`ddim_inversion`;
    ``cond_embedding`` / ``uncond_embedding``: (B, L, D).
    Returns per-step uncond embeddings (num_steps, B, L, D) to feed
    ``edit_sample``'s injection seam. With ``return_losses=True`` also
    returns the FINAL inner-loop reconstruction loss per outer step
    (num_steps,) — the optimization objective itself
    (``‖x̂_{t-1} − x_{t-1}‖²``, run_videop2p.py:596), which is the direct
    reconstruction-parity metric between the early-stopped and fixed-work
    variants: both minimize the same quantity, so comparable final losses
    mean comparable reconstruction quality.

    In dependent mode every single prediction gets the same AR-noise blend
    the inversion used — ``ε = (1-w)·ε̂ + w·ar_noise`` with a FRESH draw per
    call (the reference's ``get_noise_pred_single``/``get_noise_pred``,
    run_videop2p.py:465-487; gradients flow through the ``(1-w)·ε̂`` term
    only) — so the objective matches the model that produced the trajectory.

    ``outer_chunk``: split the outer scan into host-level jitted chunks of
    this many steps (one compile, several executions). At SD scale the full
    50-step program is a single multi-minute device call, which the TPU
    runtime's execution watchdog kills — chunking keeps each call short.
    Only valid OUTSIDE jit (the function then jits its own chunk scan).
    """
    if dependent_weight > 0.0 and dependent_sampler is None:
        raise ValueError("dependent_weight > 0 requires dependent_sampler")
    if key is None:
        key = jax.random.key(0)
    timesteps = jnp.asarray(scheduler.timesteps(num_inference_steps))
    # latent_prev for outer step i is trajectory[num - i - 1]
    # (the reference's latents[len - i - 2], run_videop2p.py:585)
    prev_seq = trajectory[::-1][1:]
    steps = jnp.arange(num_inference_steps)
    # run_videop2p.py:588 — clamped at 0 so step counts > 100 (the reference
    # hardcodes 50) cannot flip the update into gradient ascent
    lr_seq = jnp.maximum(1e-2 * (1.0 - steps / 100.0), 0.0)
    thresh_seq = epsilon + steps * 2e-5  # run_videop2p.py:603
    # Adam direction with unit lr; the decayed per-step lr scales the update
    adam = optax.adam(1.0)

    def blend(eps, key):
        if dependent_weight <= 0.0:
            return eps
        ar_noise = dependent_sampler.sample_like(key, eps)
        return (1.0 - dependent_weight) * eps + dependent_weight * ar_noise

    def outer(carry, xs):
        latent_cur, uncond, key, params, cond_embedding = carry
        t, latent_prev, lr, thresh = xs
        key, k_cond, k_fu, k_fc = jax.random.split(key, 4)
        eps, _ = unet_fn(params, latent_cur, t, cond_embedding, None)
        eps_cond_raw = jax.lax.stop_gradient(eps)
        eps_cond = blend(eps_cond_raw, k_cond)

        def loss_fn(u, k):
            eps_uncond, _ = unet_fn(params, latent_cur, t, u, None)
            eps_uncond = blend(eps_uncond, k)
            eps = eps_uncond + guidance_scale * (eps_cond - eps_uncond)
            prev_rec = scheduler.prev_step(eps, t, latent_cur, num_inference_steps)
            return jnp.mean((prev_rec - latent_prev) ** 2)

        def inner_cond(state):
            _, _, last_loss, j, _ = state
            if not early_stop:
                return j < num_inner_steps
            return jnp.logical_and(j < num_inner_steps, last_loss >= thresh)

        def inner_body(state):
            u, opt_state, _, j, k = state
            k, sub = jax.random.split(k)
            loss, grads = jax.value_and_grad(loss_fn)(u, sub)
            updates, opt_state = adam.update(grads, opt_state, u)
            u = optax.apply_updates(u, jax.tree.map(lambda g: lr * g, updates))
            return (u, opt_state, loss, j + 1, k)

        opt_state = adam.init(uncond)
        uncond, _, final_loss, _, key = jax.lax.while_loop(
            inner_cond, inner_body, (uncond, opt_state, jnp.inf, 0, key)
        )

        # advance with the optimized embedding under full CFG; the reference
        # blends the batched (2B) prediction with one batched draw — i.e.
        # independent fresh noise per half (run_videop2p.py:474-487,606-610);
        # the cond prediction is deterministic so its raw value is reused
        eps_uncond, _ = unet_fn(params, latent_cur, t, uncond, None)
        eps_uncond = blend(eps_uncond, k_fu)
        eps_c = blend(eps_cond_raw, k_fc)
        eps = eps_uncond + guidance_scale * (eps_c - eps_uncond)
        latent_cur = scheduler.prev_step(eps, t, latent_cur, num_inference_steps)
        return (latent_cur, uncond, key, params, cond_embedding), (uncond, final_loss)

    x_t = trajectory[-1]
    xs = (timesteps, prev_seq, lr_seq, thresh_seq)

    def make_body(p, cond):
        # params/cond are scan CONSTANTS (closed over per scan), never carry
        # — a carried tree is held twice inside the executable (carry-in +
        # carry-out), which for SD-scale params tips a 16 GB chip into OOM
        def body(c, x):
            lat, unc, k = c
            (lat, unc, k, _, _), y = outer((lat, unc, k, p, cond), x)
            return (lat, unc, k), y

        return body

    if not outer_chunk or outer_chunk >= num_inference_steps:
        _, (uncond_seq, losses) = jax.lax.scan(
            make_body(params, cond_embedding), (x_t, uncond_embedding, key), xs
        )
        return (uncond_seq, losses) if return_losses else uncond_seq

    # chunked path: params/cond enter as plain jit inputs (same no-carry rule
    # as above), and the jitted chunk scan is cached on the statics its
    # closure bakes in so repeat calls reuse the compiled program
    cache_key = (
        unet_fn, id(scheduler), id(dependent_sampler), float(guidance_scale),
        int(num_inner_steps), int(num_inference_steps), float(dependent_weight),
        bool(early_stop),
    )
    chunk_scan = _CHUNK_SCAN_CACHE.get(cache_key)
    if chunk_scan is None:

        def chunk_fn(p, cond, small_carry, chunk_xs):
            return jax.lax.scan(make_body(p, cond), small_carry, chunk_xs)

        while len(_CHUNK_SCAN_CACHE) >= _CHUNK_SCAN_CACHE_MAX:
            # bounded: fresh unet_fn/scheduler objects per pipeline would
            # otherwise pin executables forever in a long-lived process
            _CHUNK_SCAN_CACHE.pop(next(iter(_CHUNK_SCAN_CACHE)))
        chunk_scan = jax.jit(chunk_fn)
        _CHUNK_SCAN_CACHE[cache_key] = chunk_scan
    small = (x_t, uncond_embedding, key)
    pieces, loss_pieces = [], []
    for start in range(0, num_inference_steps, outer_chunk):
        chunk = jax.tree.map(lambda a: a[start : start + outer_chunk], xs)
        small, (seq, losses) = chunk_scan(params, cond_embedding, small, chunk)
        pieces.append(seq)
        loss_pieces.append(losses)
    uncond_seq = jnp.concatenate(pieces, axis=0)
    if return_losses:
        return uncond_seq, jnp.concatenate(loss_pieces, axis=0)
    return uncond_seq
