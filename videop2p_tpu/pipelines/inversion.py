"""DDIM inversion and null-text inversion.

TPU-native re-design of the reference ``NullInversion``
(/root/reference/run_videop2p.py:443-648) and the Stage-1 validation inversion
(/root/reference/tuneavideo/util.py:52-92):

  * ``ddim_inversion`` — 50 forward-DDIM steps, conditional-only (guidance 1),
    as a ``lax.scan`` that keeps the full latent trajectory
    (run_videop2p.py:558-578). The fork's dependent-noise blend
    ``(1-w)·ε̂ + w·ar_noise`` (run_videop2p.py:465-471) is key-threaded.
  * ``null_text_optimization`` — per-step optimization of the unconditional
    embedding (run_videop2p.py:580-612): outer scan over the 50 steps, inner
    ``lax.while_loop`` Adam with the reference's decayed lr
    ``1e-2·(1−i/100)``, ≤``num_inner_steps`` iterations and early stop at
    ``loss < ε + i·2e-5`` — the early stop becomes the while condition, so
    shapes stay static under jit.
  * ``null_text_optimization_fused`` — the same optimization as ONE jitted
    device program with the trajectory buffer donated: scan outer,
    while_loop inner, the convergence predicate carried on-device, and a
    ``null_text_precision`` knob. ``"mixed"`` runs the UNet forwards in
    bf16 (the tensors crossing the UNet boundary are cast down; pair with a
    bf16-compute ``unet_fn`` for the full MXU win) while the scheduler
    coefficients (core/ddim.py fp32 islands), the Adam state, and the
    loss/early-stop accumulation all stay float32 — the precision split
    that keeps the reconstruction inside the fixed-work PSNR band
    (tests/test_null_text_precision.py pins it at tiny scale).

The reference's Python-loop-with-break structure is the hard functionalization
case SURVEY §7 ranks #3; the while_loop preserves its exact update-then-check
semantics (loss is measured pre-update, the update it gated is still applied).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from videop2p_tpu.core.ddim import DDIMScheduler
from videop2p_tpu.core.noise import DependentNoiseSampler
from videop2p_tpu.models.attention import AttnControl
from videop2p_tpu.obs.attention import attn_step_record
from videop2p_tpu.obs.telemetry import latent_stats
from videop2p_tpu.pipelines.cached import CachedSource, filter_site_tree
from videop2p_tpu.pipelines.sampling import UNetFn
from videop2p_tpu.pipelines.stores import blend_maps_from_store

__all__ = [
    "ddim_inversion",
    "ddim_inversion_captured",
    "null_text_optimization",
    "null_text_optimization_fused",
]

# jitted programs for the outer_chunk and fused paths, keyed by the statics
# their closures bake in (runtime arrays enter as jit inputs); bounded FIFO
_CHUNK_SCAN_CACHE: dict = {}
_CHUNK_SCAN_CACHE_MAX = 4
_FUSED_PROGRAM_CACHE: dict = {}
_FUSED_PROGRAM_CACHE_MAX = 4

_NULL_TEXT_PRECISIONS = ("fp32", "mixed")
# how the per-step unconditional embedding is produced:
#   "optimize"  — the reference's per-step inner Adam loop (Mokady et al.);
#   "amortized" — closed-form negative-prompt-inversion substitute
#                 (Miyake et al., 2023): uncond := cond, under which the CFG
#                 combine collapses to the conditional prediction and the
#                 denoise replays the inversion trajectory with ZERO inner
#                 Adam steps — one forward per outer step, one fused scan;
#   "hybrid"    — amortized seed + K (hybrid_inner_steps) refinement Adam
#                 steps run JOINTLY across all outer steps as one batched
#                 program (vs 50×num_inner_steps sequential inner steps).
_NULL_TEXT_MODES = ("optimize", "amortized", "hybrid")


def _cache_put(cache: dict, cache_max: int, key, value) -> None:
    """Bounded FIFO insert: fresh unet_fn/scheduler objects per pipeline
    would otherwise pin executables forever in a long-lived process."""
    while len(cache) >= cache_max:
        cache.pop(next(iter(cache)))
    cache[key] = value


def ddim_inversion(
    unet_fn: UNetFn,
    params,
    scheduler: DDIMScheduler,
    latents: jax.Array,
    cond_embedding: jax.Array,
    *,
    num_inference_steps: int = 50,
    dependent_weight: float = 0.0,
    dependent_sampler: Optional[DependentNoiseSampler] = None,
    key: Optional[jax.Array] = None,
    return_eps: bool = False,
    attn_maps: bool = False,
):
    """Invert clean latents x_0 to noise x_T.

    ``latents``: (B, F, h, w, C) clean (VAE-encoded, scaled) latents;
    ``cond_embedding``: (B, L, D) source-prompt embedding (no CFG — the
    reference inverts with guidance 1, run_videop2p.py:558-572).

    Returns the full trajectory (num_steps+1, B, F, h, w, C) with
    ``[0] = x_0`` and ``[-1] = x_T`` (the reference's ``all_latent`` list).
    ``dependent_weight > 0`` blends the model output with AR noise:
    ``ε = (1-w)·ε̂ + w·ar_noise`` (run_videop2p.py:467-471).

    ``return_eps``: also return the per-step model outputs
    (num_steps, B, F, h, w, C), ordered along the inversion walk. DDIM's
    ``next_step``/``prev_step`` are linear in (x, ε) with identical
    coefficients, so ``prev_step(eps[i], t[i], trajectory[i+1])`` recovers
    ``trajectory[i]`` EXACTLY — a cached-ε backward replay of the source
    stream is exact where the reference's fast mode re-predicts ε from the
    drifting latent (pipeline_tuneavideo.py:412-415) and only approximately
    reconstructs. This is the seam for replaying the source stream without
    re-running its forwards (tests/test_pipelines.py pins the property).

    ``attn_maps``: also stack the per-step attention observability record
    (obs.attention — pooled per-token cross heatmaps of the source stream
    + per-site entropies, riding the scan's ``ys``) and append it to the
    return. Step axis follows the inversion walk (x_0 → x_T). Return
    order: ``trajectory[, eps_seq][, attn]``.
    """
    # latents stay float32 through the walk regardless of the UNet's compute
    # dtype — scheduler math is fp32 (the reference keeps the Stage-2 UNet and
    # latents fp32 for inversion fidelity, run_videop2p.py:111-113)
    latents = latents.astype(jnp.float32)
    # ascending timesteps: the reference walks timesteps[-(i+1)] for i in 0..N
    # (run_videop2p.py:563-566)
    timesteps = jnp.asarray(scheduler.timesteps(num_inference_steps)[::-1].copy())
    if key is None:
        key = jax.random.key(0)

    video_length = latents.shape[1]
    latent_hw = latents.shape[2:4]
    text_len = cond_embedding.shape[-2]

    def body(carry, t):
        latent, key = carry
        eps, store = unet_fn(params, latent, t, cond_embedding, None)
        if dependent_weight > 0.0:
            if dependent_sampler is None:
                raise ValueError("dependent_weight > 0 requires dependent_sampler")
            key, sub = jax.random.split(key)
            ar_noise = dependent_sampler.sample_like(sub, eps)
            eps = (1.0 - dependent_weight) * eps + dependent_weight * ar_noise
        latent = scheduler.next_step(eps, t, latent, num_inference_steps)
        # return_eps/attn_maps are static: without them the scan must not
        # stack dead buffers (eager callers get no DCE)
        ys = {"latent": latent}
        if return_eps:
            ys["eps"] = eps.astype(jnp.float32)
        if attn_maps:
            ys["attn"] = attn_step_record(
                store, num_uncond=0, num_cond=latent.shape[0],
                video_length=video_length, text_len=text_len,
                latent_hw=latent_hw,
            )
        return (latent, key), ys

    (_, _), ys = jax.lax.scan(body, (latents, key), timesteps)
    full = jnp.concatenate([latents[None], ys["latent"]], axis=0)
    out = (full,)
    if return_eps:
        out += (ys["eps"],)
    if attn_maps:
        out += (ys["attn"],)
    return out if len(out) > 1 else full


def ddim_inversion_captured(
    unet_fn: UNetFn,
    params,
    scheduler: DDIMScheduler,
    latents: jax.Array,
    cond_embedding: jax.Array,
    *,
    num_inference_steps: int = 50,
    cross_len: int = 0,
    self_window: Tuple[int, int] = (0, 0),
    capture_blend: bool = False,
    blend_res: Optional[Tuple[int, int]] = None,
    dependent_weight: float = 0.0,
    dependent_sampler: Optional[DependentNoiseSampler] = None,
    key: Optional[jax.Array] = None,
    temporal_maps_dtype=None,
    attn_maps: bool = False,
) -> Tuple[jax.Array, CachedSource]:
    """DDIM inversion that also captures everything a cached-source edit
    needs (see :mod:`videop2p_tpu.pipelines.cached` for the design).

    ``attn_maps``: additionally stack the per-step attention
    observability record of the SOURCE stream (obs.attention — pooled
    per-token cross heatmaps + per-site entropies; in cached fast mode
    this is the only place source-stream maps are visible, the edit batch
    having dropped the stream) and return it as a third element,
    step-axis in inversion-walk order.

    ``temporal_maps_dtype``: optional narrower STORAGE dtype for the
    captured temporal (attn_temp) probability maps — e.g.
    ``jnp.float8_e4m3fn``. The temporal tree is the long-video memory
    cliff: per spatial position it holds an F×F map, so its bytes grow
    quadratically with frame count (8f: 0.6 GiB → 24f: 5.8 GiB at SD
    scale) while everything else grows linearly. Probabilities live in
    [0, 1] where e4m3's 3 mantissa bits give a ~6 % relative step (about
    one significant decimal digit), and values below ~2e-3 land in
    subnormals or flush to zero — the real acceptance gate is the
    empirical edit-output delta test (tests/test_cached.py), not a digits
    figure; the maps are read back
    upcast to the sibling captured maps' dtype (cached.py ``base_tree_at``), they only
    feed the EDIT stream's map replacement, and the source-stream replay
    is ε-based — its bit-exactness guarantee is unaffected
    (tests/test_cached.py pins both properties).

    Same walk as :func:`ddim_inversion`, but split into segments so that the
    full per-head controlled-site probabilities are stacked ONLY for the
    inversion steps whose maps the edit's gates will actually read:

      * cross maps for edit steps [0, ``cross_len``) — inversion steps
        [N−cross_len, N);
      * temporal maps for edit steps [lo, hi) = ``self_window`` — inversion
        steps [N−hi, N−lo);
      * per-step LocalBlend store contributions for every step when
        ``capture_blend`` (head-meaned and blend-site-stacked first — tiny).

    Edit step *i* reads the maps captured at inversion step ``N−1−i``: the
    same timestep, with the latent one trajectory position earlier than a
    live source stream would use (the disclosed approximation; the latent
    replay itself is exact). Returns ``(trajectory, CachedSource)``.
    """
    if dependent_weight > 0.0 and dependent_sampler is None:
        raise ValueError("dependent_weight > 0 requires dependent_sampler")
    N = num_inference_steps
    lo, hi = self_window
    if not (0 <= lo <= hi <= N):
        raise ValueError(f"self_window {self_window} outside [0, {N}]")
    if not (0 <= cross_len <= N):
        raise ValueError(f"cross_len {cross_len} outside [0, {N}]")
    latents = latents.astype(jnp.float32)
    video_length = latents.shape[1]
    latent_hw = latents.shape[2:4]
    text_len = cond_embedding.shape[-2]
    timesteps = jnp.asarray(scheduler.timesteps(N)[::-1].copy())
    if key is None:
        key = jax.random.key(0)

    def run_segment(latent, key, ts, want_cross, want_temporal):
        capture = want_cross or want_temporal

        def body(carry, t):
            latent, key = carry
            control = (
                AttnControl(ctx=None, step_index=jnp.asarray(0, jnp.int32), capture=True)
                if capture
                else None
            )
            eps, store = unet_fn(params, latent, t, cond_embedding, control)
            if dependent_weight > 0.0:
                key, sub = jax.random.split(key)
                ar_noise = dependent_sampler.sample_like(sub, eps)
                eps = (1.0 - dependent_weight) * eps + dependent_weight * ar_noise
            latent = scheduler.next_step(eps, t, latent, N)
            ys = {"latent": latent}
            if attn_maps:
                ys["attn"] = attn_step_record(
                    store, num_uncond=0, num_cond=latent.shape[0],
                    video_length=video_length, text_len=text_len,
                    latent_hw=latent_hw,
                )
            if capture_blend:
                ys["blend"] = blend_maps_from_store(
                    store,
                    latent_hw=latent_hw,
                    video_length=video_length,
                    num_prompts=1,
                    text_len=text_len,
                    blend_res=blend_res,
                    num_uncond=0,
                )
            if want_cross:
                ys["cross"] = filter_site_tree(store["attn_base"], "attn2")
            if want_temporal:
                t_tree = filter_site_tree(store["attn_base"], "attn_temp")
                if temporal_maps_dtype is not None:
                    if jnp.issubdtype(jnp.dtype(temporal_maps_dtype),
                                      jnp.integer):
                        # int8 fixed-point: probabilities in [0,1] scale to
                        # round(p·127) — a uniform 1/254 absolute step;
                        # CachedSource.base_tree_at divides back by 127
                        t_tree = jax.tree.map(
                            lambda a: jnp.clip(
                                jnp.round(a.astype(jnp.float32) * 127.0),
                                -127.0, 127.0,
                            ).astype(temporal_maps_dtype),
                            t_tree,
                        )
                    else:
                        t_tree = jax.tree.map(
                            lambda a: a.astype(temporal_maps_dtype), t_tree
                        )
                ys["temporal"] = t_tree
            return (latent, key), ys

        return jax.lax.scan(body, (latent, key), ts)

    # segment the walk at the capture-window edges (inversion-step space):
    # cross maps live in [N−cross_len, N), temporal in [N−hi, N−lo)
    bounds = sorted({0, N - hi, N - lo, N - cross_len, N})
    carry = (latents, key)
    lat_pieces, blend_pieces, cross_pieces, temporal_pieces = [], [], [], []
    attn_pieces = []
    for s, e in zip(bounds[:-1], bounds[1:]):
        want_cross = s >= N - cross_len
        want_temporal = s >= N - hi and e <= N - lo
        carry, ys = run_segment(*carry, timesteps[s:e], want_cross, want_temporal)
        lat_pieces.append(ys["latent"])
        if attn_maps:
            attn_pieces.append(ys["attn"])
        if capture_blend:
            blend_pieces.append(ys["blend"])
        if want_cross:
            cross_pieces.append(ys["cross"])
        if want_temporal:
            temporal_pieces.append(ys["temporal"])

    trajectory = jnp.concatenate([latents[None]] + lat_pieces, axis=0)

    def stack_reversed(pieces):
        # inversion order → edit order (edit step i ↔ inversion step N−1−i)
        if not pieces:
            return None
        return jax.tree.map(lambda *xs: jnp.flip(jnp.concatenate(xs, axis=0), axis=0), *pieces)

    cached = CachedSource(
        src_latents=jnp.flip(trajectory, axis=0),
        cross_maps=stack_reversed(cross_pieces),
        temporal_maps=stack_reversed(temporal_pieces),
        blend_seq=stack_reversed(blend_pieces) if capture_blend else None,
        cross_len=cross_len,
        self_window=(lo, hi),
    )
    if attn_maps:
        attn = jax.tree.map(
            lambda *xs: jnp.concatenate(xs, axis=0), *attn_pieces
        )
        return trajectory, cached, attn
    return trajectory, cached


def null_text_optimization(
    unet_fn: UNetFn,
    params,
    scheduler: DDIMScheduler,
    trajectory: jax.Array,
    cond_embedding: jax.Array,
    uncond_embedding: jax.Array,
    *,
    num_inference_steps: int = 50,
    guidance_scale: float = 7.5,
    num_inner_steps: int = 10,
    epsilon: float = 1e-5,
    null_text_precision: str = "fp32",
    null_text_mode: str = "optimize",
    hybrid_inner_steps: int = 3,
    dependent_weight: float = 0.0,
    dependent_sampler: Optional[DependentNoiseSampler] = None,
    key: Optional[jax.Array] = None,
    outer_chunk: Optional[int] = None,
    early_stop: bool = True,
    return_losses: bool = False,
    return_inner_steps: bool = False,
    telemetry: bool = False,
) -> jax.Array:
    """Optimize a per-step unconditional embedding that makes CFG denoising
    replay the recorded inversion trajectory (run_videop2p.py:580-612).

    ``early_stop=False`` runs exactly ``num_inner_steps`` inner iterations
    per outer step (no ``loss < ε + i·2e-5`` break): the work becomes
    weight-independent, giving a stable wall-clock for benchmarking — the
    reference-faithful early-stopped run varies 157–418 s with the random
    early-stop point (run_videop2p.py:603).

    ``trajectory``: (num_steps+1, B, F, h, w, C) from :func:`ddim_inversion`;
    ``cond_embedding`` / ``uncond_embedding``: (B, L, D).
    Returns per-step uncond embeddings (num_steps, B, L, D) to feed
    ``edit_sample``'s injection seam. With ``return_losses=True`` also
    returns the FINAL inner-loop reconstruction loss per outer step
    (num_steps,) — the optimization objective itself
    (``‖x̂_{t-1} − x_{t-1}‖²``, run_videop2p.py:596), which is the direct
    reconstruction-parity metric between the early-stopped and fixed-work
    variants: both minimize the same quantity, so comparable final losses
    mean comparable reconstruction quality.

    In dependent mode every single prediction gets the same AR-noise blend
    the inversion used — ``ε = (1-w)·ε̂ + w·ar_noise`` with a FRESH draw per
    call (the reference's ``get_noise_pred_single``/``get_noise_pred``,
    run_videop2p.py:465-487; gradients flow through the ``(1-w)·ε̂`` term
    only) — so the objective matches the model that produced the trajectory.

    ``null_text_precision``: ``"fp32"`` (default — the reference's Stage-2
    behavior) or ``"mixed"``. Mixed casts the tensors crossing the UNet
    boundary (latents and text embeddings) to bf16 before every forward and
    upcasts the predictions back; the scheduler steps (fp32 islands,
    core/ddim.py), the Adam moments, the CFG combine, and the loss /
    early-stop accumulation all stay float32. With a bf16-compute
    ``unet_fn`` this runs the inner-loop forwards+backward at full MXU
    rate; with an fp32 ``unet_fn`` it still bounds the activation dtype at
    the boundary (the parity test gates both).

    ``return_inner_steps``: also return the number of inner Adam updates
    each outer step actually took (num_steps,) int32 — the early-stop
    observability the fused-vs-host parity test pins.

    ``telemetry``: additionally stack per-outer-step latent statistics
    (obs.telemetry.latent_stats of the advanced ``latent_cur`` — abs-max,
    mean, NaN/inf counts) as a fourth output. The stats ride the outer
    scan's ``ys`` — zero extra dispatches — and are scalars per step, so
    the program output grows by bytes. Off by default: the telemetry-off
    program is the exact pre-telemetry program (tests/test_obs.py pins
    bit-exactness).

    ``outer_chunk``: split the outer scan into host-level jitted chunks of
    this many steps (one compile, several executions). At SD scale the full
    50-step program is a single multi-minute device call, which the TPU
    runtime's execution watchdog kills — chunking keeps each call short.
    Only valid OUTSIDE jit (the function then jits its own chunk scan).
    For the single-dispatch donated-buffer variant see
    :func:`null_text_optimization_fused`.

    ``null_text_mode``: how the embedding sequence is produced.

      * ``"optimize"`` (default) — the reference's per-step inner Adam loop,
        exactly as documented above (every other knob applies unchanged).
      * ``"amortized"`` — the closed-form negative-prompt-inversion
        substitute (Miyake et al., 2023): the unconditional embedding is set
        to the SOURCE conditional embedding at every step, under which the
        CFG combine ``ε_u + g·(ε_c − ε_u)`` collapses to ``ε_c`` and the
        denoise replays the inversion trajectory to NPI accuracy with zero
        inner Adam steps. One forward per outer step (vs ``2 +
        3·num_inner_steps`` forward-equivalents), one fused scan; the
        returned ``final_loss`` per step is the SAME reconstruction
        objective the optimizer would have minimized — the direct parity
        record. ``num_inner_steps``/``epsilon``/``early_stop`` are inert;
        ``inner_steps`` reads 0 everywhere.
      * ``"hybrid"`` — amortized seed + ``hybrid_inner_steps`` (K ≤ 3
        recommended) refinement Adam steps run JOINTLY across all outer
        steps: each step optimizes its embedding against the RECORDED
        trajectory latents (the amortized fixed point), so the 50 outer
        optimizations lose their sequential dependence and batch into one
        K-iteration program — K sequential gradient phases instead of
        ``50 × num_inner_steps``. ``final_loss`` is each step's last
        pre-update loss (the ``"optimize"`` convention); ``inner_steps``
        reads K everywhere (no early stop — the batch is joint).

    Both non-default modes trade a bounded reconstruction-accuracy delta
    (pinned as a PSNR band in tests/test_null_text_precision.py and gated
    by the quality rules, tools/obs_diff.py) for a ≥3× inner-loop flop
    reduction; ``outer_chunk`` composes with every mode (chunked ==
    unchunked, per-step math identical).
    """
    if null_text_precision not in _NULL_TEXT_PRECISIONS:
        raise ValueError(
            f"null_text_precision {null_text_precision!r} not in "
            f"{_NULL_TEXT_PRECISIONS}"
        )
    if null_text_mode not in _NULL_TEXT_MODES:
        raise ValueError(
            f"null_text_mode {null_text_mode!r} not in {_NULL_TEXT_MODES}"
        )
    if dependent_weight > 0.0 and dependent_sampler is None:
        raise ValueError("dependent_weight > 0 requires dependent_sampler")
    if key is None:
        key = jax.random.key(0)
    timesteps = jnp.asarray(scheduler.timesteps(num_inference_steps))
    # the optimized variable and its Adam moments are float32 in EVERY
    # precision mode (a bf16 text encoder hands over a bf16 uncond); the
    # trajectory targets likewise — loss accumulation must be fp32
    uncond_embedding = uncond_embedding.astype(jnp.float32)
    trajectory = trajectory.astype(jnp.float32)
    # latent_prev for outer step i is trajectory[num - i - 1]
    # (the reference's latents[len - i - 2], run_videop2p.py:585)
    prev_seq = trajectory[::-1][1:]
    steps = jnp.arange(num_inference_steps)
    # run_videop2p.py:588 — clamped at 0 so step counts > 100 (the reference
    # hardcodes 50) cannot flip the update into gradient ascent
    lr_seq = jnp.maximum(1e-2 * (1.0 - steps / 100.0), 0.0)
    thresh_seq = epsilon + steps * 2e-5  # run_videop2p.py:603
    # Adam direction with unit lr; the decayed per-step lr scales the update;
    # moments and updates live in the embedding's own float32 — the Adam
    # state is never narrowed in mixed mode
    adam = optax.adam(1.0)
    # mixed precision: only the tensors CROSSING the UNet boundary narrow to
    # bf16; predictions upcast to float32 the moment they come back, so the
    # CFG combine, the scheduler islands, and the loss all accumulate fp32
    mixed = null_text_precision == "mixed"
    cast_in = (lambda a: a.astype(jnp.bfloat16)) if mixed else (lambda a: a)

    def fwd(params, latent, t, text):
        eps, _ = unet_fn(params, cast_in(latent), t, cast_in(text), None)
        return eps.astype(jnp.float32)

    def blend(eps, key):
        if dependent_weight <= 0.0:
            return eps
        ar_noise = dependent_sampler.sample_like(key, eps)
        return (1.0 - dependent_weight) * eps + dependent_weight * ar_noise

    def outer(carry, xs):
        latent_cur, uncond, key, params, cond_embedding = carry
        t, latent_prev, lr, thresh = xs
        key, k_cond, k_fu, k_fc = jax.random.split(key, 4)
        eps_cond_raw = jax.lax.stop_gradient(
            fwd(params, latent_cur, t, cond_embedding)
        )
        eps_cond = blend(eps_cond_raw, k_cond)

        def loss_fn(u, k):
            eps_uncond = blend(fwd(params, latent_cur, t, u), k)
            eps = eps_uncond + guidance_scale * (eps_cond - eps_uncond)
            prev_rec = scheduler.prev_step(eps, t, latent_cur, num_inference_steps)
            return jnp.mean((prev_rec - latent_prev) ** 2)

        def inner_cond(state):
            _, _, last_loss, j, _ = state
            if not early_stop:
                return j < num_inner_steps
            return jnp.logical_and(j < num_inner_steps, last_loss >= thresh)

        def inner_body(state):
            u, opt_state, _, j, k = state
            k, sub = jax.random.split(k)
            loss, grads = jax.value_and_grad(loss_fn)(u, sub)
            updates, opt_state = adam.update(grads, opt_state, u)
            u = optax.apply_updates(u, jax.tree.map(lambda g: lr * g, updates))
            return (u, opt_state, loss, j + 1, k)

        opt_state = adam.init(uncond)
        uncond, _, final_loss, inner_taken, key = jax.lax.while_loop(
            inner_cond,
            inner_body,
            (uncond, opt_state, jnp.asarray(jnp.inf, jnp.float32),
             jnp.asarray(0, jnp.int32), key),
        )

        # advance with the optimized embedding under full CFG; the reference
        # blends the batched (2B) prediction with one batched draw — i.e.
        # independent fresh noise per half (run_videop2p.py:474-487,606-610);
        # the cond prediction is deterministic so its raw value is reused
        eps_uncond = blend(fwd(params, latent_cur, t, uncond), k_fu)
        eps_c = blend(eps_cond_raw, k_fc)
        eps = eps_uncond + guidance_scale * (eps_c - eps_uncond)
        latent_cur = scheduler.prev_step(eps, t, latent_cur, num_inference_steps)
        ys = (uncond, final_loss, inner_taken)
        if telemetry:
            # scalar stats ride the scan output — no extra dispatch, and
            # a fused-scan NaN becomes visible with the step it appeared at
            ys += (latent_stats(latent_cur),)
        return (latent_cur, uncond, key, params, cond_embedding), ys

    def outer_amortized(carry, xs):
        # negative-prompt-inversion closed form: uncond := cond, so the CFG
        # combine collapses to the conditional prediction — ONE forward per
        # outer step, zero inner Adam steps. The per-step loss is the same
        # reconstruction objective the optimizer minimizes (the replay's
        # residual against the recorded trajectory), so the record stays
        # directly comparable to the "optimize" mode's final_loss.
        latent_cur, _uncond, key, params, cond_embedding = carry
        t, latent_prev, _lr, _thresh = xs
        key, k_fu, k_fc = jax.random.split(key, 3)
        eps_cond_raw = fwd(params, latent_cur, t, cond_embedding)
        uncond_out = cond_embedding.astype(jnp.float32)
        # dependent mode: the CFG halves draw independent fresh noise, the
        # same structure as the optimize mode's final advance
        eps_uncond = blend(eps_cond_raw, k_fu)
        eps_c = blend(eps_cond_raw, k_fc)
        eps = eps_uncond + guidance_scale * (eps_c - eps_uncond)
        prev_rec = scheduler.prev_step(eps, t, latent_cur, num_inference_steps)
        final_loss = jnp.mean((prev_rec - latent_prev) ** 2)
        ys = (uncond_out, final_loss, jnp.asarray(0, jnp.int32))
        if telemetry:
            ys += (latent_stats(prev_rec),)
        return (prev_rec, uncond_out, key, params, cond_embedding), ys

    outer_fn = outer if null_text_mode == "optimize" else outer_amortized

    x_t = trajectory[-1]
    xs = (timesteps, prev_seq, lr_seq, thresh_seq)

    def make_body(p, cond):
        # params/cond are scan CONSTANTS (closed over per scan), never carry
        # — a carried tree is held twice inside the executable (carry-in +
        # carry-out), which for SD-scale params tips a 16 GB chip into OOM
        def body(c, x):
            lat, unc, k = c
            (lat, unc, k, _, _), y = outer_fn((lat, unc, k, p, cond), x)
            return (lat, unc, k), y

        return body

    def pack(uncond_seq, losses, inner_taken, tel=None):
        out = (uncond_seq,)
        if return_losses:
            out += (losses,)
        if return_inner_steps:
            out += (inner_taken,)
        if telemetry:
            out += (tel,)
        return out if len(out) > 1 else out[0]

    if null_text_mode == "hybrid":
        K = int(hybrid_inner_steps)
        if K < 1:
            raise ValueError(f"hybrid_inner_steps must be >= 1, got {K}")
        # every step optimizes against the RECORDED trajectory latents (the
        # amortized fixed point, where the CFG replay already tracks the
        # trajectory), so the outer steps lose the sequential dependence the
        # "optimize" mode carries through latent_cur: K gradient phases over
        # a step-batched embedding replace N×num_inner_steps sequential
        # inner steps. Per-step math is chunk-invariant (absolute-index
        # keys, independent steps), so chunked == unchunked exactly.
        lat_cur_seq = trajectory[::-1][:-1]  # latent entering outer step i
        step_keys = jax.vmap(lambda i: jax.random.fold_in(key, i))(steps)

        def hybrid_chunk_fn(p, cond, chunk_xs):
            t_c, lat_c, prev_c, lr_c, k_c = chunk_xs
            ks = jax.vmap(lambda k: jax.random.split(k, 2))(k_c)

            def cond_eps(lat, t, k):
                return blend(jax.lax.stop_gradient(fwd(p, lat, t, cond)), k)

            eps_cond = jax.vmap(cond_eps)(lat_c, t_c, ks[:, 0])
            # amortized seed: uncond := cond at every step
            u0 = jnp.broadcast_to(
                cond.astype(jnp.float32), (t_c.shape[0],) + cond.shape
            )
            opt_state = adam.init(u0)

            def loss_one(u, lat, t, ec, lp, k):
                eps_uncond = blend(fwd(p, lat, t, u), k)
                eps = eps_uncond + guidance_scale * (ec - eps_uncond)
                prev_rec = scheduler.prev_step(
                    eps, t, lat, num_inference_steps
                )
                return jnp.mean((prev_rec - lp) ** 2), prev_rec

            grad_one = jax.value_and_grad(loss_one, has_aux=True)

            def iter_body(carry, _):
                u_seq, opt_state, kseq = carry
                kpair = jax.vmap(lambda k: jax.random.split(k, 2))(kseq)
                (losses, prev_recs), grads = jax.vmap(grad_one)(
                    u_seq, lat_c, t_c, eps_cond, prev_c, kpair[:, 0]
                )
                updates, opt_state = adam.update(grads, opt_state, u_seq)
                u_seq = optax.apply_updates(
                    u_seq,
                    jax.tree.map(
                        lambda g: lr_c[:, None, None, None] * g, updates
                    ),
                )
                ys = (losses,)
                if telemetry:
                    # scalars only in the iteration ys — stacking prev_recs
                    # across K would hold K extra trajectories in HBM
                    ys += (jax.vmap(latent_stats)(prev_recs),)
                return (u_seq, opt_state, kpair[:, 1]), ys

            (u_seq, _, _), it_ys = jax.lax.scan(
                iter_body, (u0, opt_state, ks[:, 1]), None, length=K
            )
            # the "optimize" convention: final_loss is the last executed
            # iteration's pre-update loss
            outs = (
                u_seq,
                it_ys[0][-1],
                jnp.full((t_c.shape[0],), K, jnp.int32),
            )
            if telemetry:
                outs += (jax.tree.map(lambda a: a[-1], it_ys[1]),)
            return outs

        hybrid_xs = (timesteps, lat_cur_seq, prev_seq, lr_seq, step_keys)
        if not outer_chunk or outer_chunk >= num_inference_steps:
            return pack(*hybrid_chunk_fn(params, cond_embedding, hybrid_xs))
        cache_key = (
            "hybrid", unet_fn, id(scheduler), id(dependent_sampler),
            float(guidance_scale), K, int(num_inference_steps),
            float(dependent_weight), null_text_precision, bool(telemetry),
        )
        chunk_prog = _CHUNK_SCAN_CACHE.get(cache_key)
        if chunk_prog is None:
            from videop2p_tpu.obs.ledger import instrumented_jit

            chunk_prog = instrumented_jit(
                hybrid_chunk_fn, program="null_text_chunked"
            )
            _cache_put(_CHUNK_SCAN_CACHE, _CHUNK_SCAN_CACHE_MAX,
                       cache_key, chunk_prog)
        pieces = None
        for start in range(0, num_inference_steps, outer_chunk):
            chunk = jax.tree.map(
                lambda a: a[start : start + outer_chunk], hybrid_xs
            )
            ys = chunk_prog(params, cond_embedding, chunk)
            if pieces is None:
                pieces = [[] for _ in ys]
            for lst, y in zip(pieces, ys):
                lst.append(y)
        return pack(*(
            jax.tree.map(lambda *xs_: jnp.concatenate(xs_, axis=0), *lst)
            for lst in pieces
        ))

    if not outer_chunk or outer_chunk >= num_inference_steps:
        _, ys = jax.lax.scan(
            make_body(params, cond_embedding), (x_t, uncond_embedding, key), xs
        )
        return pack(*ys)

    # chunked path: params/cond enter as plain jit inputs (same no-carry rule
    # as above), and the jitted chunk scan is cached on the statics its
    # closure bakes in so repeat calls reuse the compiled program
    cache_key = (
        unet_fn, id(scheduler), id(dependent_sampler), float(guidance_scale),
        int(num_inner_steps), int(num_inference_steps), float(dependent_weight),
        bool(early_stop), null_text_precision, null_text_mode, bool(telemetry),
    )
    chunk_scan = _CHUNK_SCAN_CACHE.get(cache_key)
    if chunk_scan is None:

        def chunk_fn(p, cond, small_carry, chunk_xs):
            return jax.lax.scan(make_body(p, cond), small_carry, chunk_xs)

        # instrumented: with an active ledger each chunk dispatch records a
        # program_call, and the compile (first chunk) is mined into a
        # program_analysis event (obs/introspect.py); with no ledger this
        # is jax.jit plus one attribute lookup per call
        from videop2p_tpu.obs.ledger import instrumented_jit

        chunk_scan = instrumented_jit(chunk_fn, program="null_text_chunked")
        _cache_put(_CHUNK_SCAN_CACHE, _CHUNK_SCAN_CACHE_MAX, cache_key, chunk_scan)
    small = (x_t, uncond_embedding, key)
    piece_lists = None
    for start in range(0, num_inference_steps, outer_chunk):
        chunk = jax.tree.map(lambda a: a[start : start + outer_chunk], xs)
        small, ys = chunk_scan(params, cond_embedding, small, chunk)
        if piece_lists is None:
            piece_lists = [[] for _ in ys]
        for lst, y in zip(piece_lists, ys):
            lst.append(y)
    return pack(*(
        jax.tree.map(lambda *xs_: jnp.concatenate(xs_, axis=0), *lst)
        for lst in piece_lists
    ))


def null_text_optimization_fused(
    unet_fn: UNetFn,
    params,
    scheduler: DDIMScheduler,
    trajectory: jax.Array,
    cond_embedding: jax.Array,
    uncond_embedding: jax.Array,
    *,
    num_inference_steps: int = 50,
    guidance_scale: float = 7.5,
    num_inner_steps: int = 10,
    epsilon: float = 1e-5,
    null_text_precision: str = "fp32",
    null_text_mode: str = "optimize",
    hybrid_inner_steps: int = 3,
    dependent_weight: float = 0.0,
    dependent_sampler: Optional[DependentNoiseSampler] = None,
    key: Optional[jax.Array] = None,
    early_stop: bool = True,
    donate: bool = True,
    return_stats: bool = False,
    telemetry: bool = False,
):
    """Null-text optimization as ONE jitted, donated-carry device program.

    The host-driven structure (an outer Python/jit-chunk loop re-dispatching
    per segment) pays a tunnel round trip per dispatch and re-uploads the
    scan constants each time; here the whole 50-step outer scan — inner
    bounded ``lax.while_loop`` Adam with the convergence predicate carried
    on-device — compiles to a single XLA program, dispatched once. The
    trajectory buffer (the largest input, ~270 MB at SD scale 8f) is DONATED
    to the program by default: XLA reuses it for scan temporaries instead of
    holding input + workspace side by side. Callers that still need the
    trajectory afterwards must pass ``donate=False`` (the CLI extracts x_T
    before optimizing, so its buffer is free to donate).

    Precision follows ``null_text_precision`` exactly as in
    :func:`null_text_optimization` (which this wraps): bf16 UNet forwards in
    ``"mixed"`` with fp32 scheduler coefficients (core/ddim.py islands),
    fp32 Adam state, and fp32 loss/early-stop accumulation.
    ``null_text_mode``/``hybrid_inner_steps`` select the amortized
    (closed-form negative-prompt) or hybrid (joint K-step refinement)
    substitutes, likewise passed through — every mode compiles to one
    donated-trajectory device program here.

    Watchdog note: at SD scale the fp32 fixed-10 program can be a
    multi-minute single device call — the TPU runtime's execution watchdog
    territory that motivated ``outer_chunk``. The mixed program cuts that
    wall-clock ~3-4×; if a deployment still trips the watchdog, fall back to
    ``null_text_optimization(outer_chunk=...)`` (the CLI exposes
    ``--null_text_chunk`` for exactly this).

    Returns the per-step uncond embeddings (num_steps, B, L, D); with
    ``return_stats=True`` returns ``(uncond_seq, stats)`` where ``stats`` is
    ``{"final_loss": (num_steps,) float32, "inner_steps": (num_steps,)
    int32}`` — the reconstruction objective per outer step and the inner
    Adam updates its early stop actually took. ``telemetry=True``
    (requires ``return_stats``) adds ``stats["latent_stats"]`` — per-outer-
    step latent abs-max/mean/NaN/inf scalars stacked inside the SAME fused
    program (obs.telemetry; zero extra dispatches, off by default so the
    donated fast path is untouched).
    """
    if null_text_precision not in _NULL_TEXT_PRECISIONS:
        raise ValueError(
            f"null_text_precision {null_text_precision!r} not in "
            f"{_NULL_TEXT_PRECISIONS}"
        )
    if null_text_mode not in _NULL_TEXT_MODES:
        raise ValueError(
            f"null_text_mode {null_text_mode!r} not in {_NULL_TEXT_MODES}"
        )
    if dependent_weight > 0.0 and dependent_sampler is None:
        raise ValueError("dependent_weight > 0 requires dependent_sampler")
    if telemetry and not return_stats:
        raise ValueError(
            "telemetry=True surfaces through the stats record — pass "
            "return_stats=True (silently computing-and-dropping telemetry "
            "would still change the compiled program)"
        )
    if key is None:
        key = jax.random.key(0)
    # the CPU backend cannot alias donated buffers — requesting donation
    # there only produces an unusable-donation warning per call
    donate = donate and jax.default_backend() != "cpu"

    cache_key = (
        unet_fn, id(scheduler), id(dependent_sampler), float(guidance_scale),
        int(num_inner_steps), int(num_inference_steps), float(dependent_weight),
        float(epsilon), bool(early_stop), null_text_precision, null_text_mode,
        int(hybrid_inner_steps), bool(donate), bool(telemetry),
    )
    program = _FUSED_PROGRAM_CACHE.get(cache_key)
    if program is None:

        def program_fn(p, cond, traj, uncond, k):
            return null_text_optimization(
                unet_fn, p, scheduler, traj, cond, uncond,
                num_inference_steps=num_inference_steps,
                guidance_scale=guidance_scale,
                num_inner_steps=num_inner_steps,
                epsilon=epsilon,
                null_text_precision=null_text_precision,
                null_text_mode=null_text_mode,
                hybrid_inner_steps=hybrid_inner_steps,
                dependent_weight=dependent_weight,
                dependent_sampler=dependent_sampler,
                key=k,
                early_stop=early_stop,
                return_losses=True,
                return_inner_steps=True,
                telemetry=telemetry,
            )

        # argnum 2 = the trajectory, the only buffer worth donating (the
        # uncond embedding is KB-scale and callers routinely reuse theirs).
        # instrumented_jit: the fused program jits inside this cache where
        # the CLI's wrappers cannot reach it — instrumenting HERE is what
        # lands its program_call / program_analysis ledger events (the
        # analysis abstracts its arguments first, so donation is safe)
        from videop2p_tpu.obs.ledger import instrumented_jit

        program = instrumented_jit(
            program_fn, program="null_text_fused",
            donate_argnums=(2,) if donate else ()
        )
        _cache_put(_FUSED_PROGRAM_CACHE, _FUSED_PROGRAM_CACHE_MAX,
                   cache_key, program)

    outs = program(params, cond_embedding, trajectory, uncond_embedding, key)
    uncond_seq, losses, inner_taken = outs[:3]
    if return_stats:
        stats = {"final_loss": losses, "inner_steps": inner_taken}
        if telemetry:
            stats["latent_stats"] = outs[3]
        return uncond_seq, stats
    return uncond_seq
