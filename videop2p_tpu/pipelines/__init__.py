"""Latent-space pipelines: controlled sampling, DDIM inversion, null-text."""

from videop2p_tpu.pipelines.cached import CachedSource
from videop2p_tpu.pipelines.inversion import (
    ddim_inversion,
    ddim_inversion_captured,
    null_text_optimization,
    null_text_optimization_fused,
)
from videop2p_tpu.pipelines.fast import cached_fast_edit
from videop2p_tpu.pipelines.sampling import edit_sample, make_unet_fn, official_edit
from videop2p_tpu.pipelines.stores import blend_maps_from_store, flatten_store

__all__ = [
    "CachedSource",
    "cached_fast_edit",
    "ddim_inversion",
    "ddim_inversion_captured",
    "null_text_optimization",
    "null_text_optimization_fused",
    "edit_sample",
    "make_unet_fn",
    "official_edit",
    "blend_maps_from_store",
    "flatten_store",
]
