"""The fused cached-source fast edit: capture-inversion + controlled edit
as one traceable function.

One device program = one host dispatch (each dispatch rides the TPU tunnel
at ~0.5–1 s on this harness), and the multi-GiB capture trees never surface
as program outputs. Shared by the CLI (cli/run_videop2p.py) and the bench
(bench.py) so the benchmarked program IS the program users run — the two
cannot drift apart.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax

from videop2p_tpu.control.controllers import ControlContext
from videop2p_tpu.core.ddim import DDIMScheduler
from videop2p_tpu.core.noise import DependentNoiseSampler
from videop2p_tpu.pipelines.inversion import ddim_inversion_captured
from videop2p_tpu.pipelines.sampling import UNetFn, edit_sample

__all__ = [
    "cached_fast_edit",
    "capture_shapes",
    "maps_budget_decision",
    "choose_cached_maps",
]


def choose_cached_maps(shapes_for, *, sp: int = 1, budget_gb: float = 6.0):
    """Escalating cached-mode decision shared by the CLI and bench: try
    full-precision (bf16) capture first; if the per-chip budget refuses,
    retry with the temporal maps stored at one byte per probability — the
    quadratic-in-frames tree is 8f: 0.6 GiB → 24f: 5.8 GiB at bf16 SD
    scale, 0.3 GiB → 2.9 GiB at 1 byte. Two 1-byte encodings, tried in
    order:

      * ``float8_e4m3fn`` (where this jax exposes it): ~6 % relative step
        on [0,1] probabilities — about one significant decimal digit, with
        sub-~2e-3 values in subnormals;
      * ``int8`` fixed-point (always available): ``round(p·127)`` — a
        UNIFORM 1/254 ≈ 0.004 absolute step, so mid-range probabilities
        quantize FINER than e4m3 while tiny ones coarser; encode/decode at
        the capture/replay seams (pipelines/inversion.py ↔
        ``CachedSource.base_tree_at``).

    Both are acceptable because the empirical edit-output delta test
    (tests/test_cached.py) gates them, and only the edit stream's map
    replacement reads them, never the exact source replay.

    ``shapes_for(temporal_maps_dtype)`` must return the
    :func:`capture_shapes` CachedSource shape tree for that storage dtype.

    Returns ``(use_cached, temporal_maps_dtype, map_gb, per_chip_gb)`` —
    dtype None means full precision.
    """
    import jax.numpy as jnp

    candidates = [None]
    if hasattr(jnp, "float8_e4m3fn"):
        candidates.append(jnp.float8_e4m3fn)
    candidates.append(jnp.int8)
    for dt in candidates:
        fits, map_gb, per_chip_gb = maps_budget_decision(
            shapes_for(dt), sp=sp, budget_gb=budget_gb
        )
        if fits:
            return True, dt, map_gb, per_chip_gb
    return False, None, map_gb, per_chip_gb


def maps_budget_decision(cached_shapes, *, sp: int = 1,
                         budget_gb: float = 6.0):
    """The cached-mode HBM gate, shared by the CLI and tests: given the
    :func:`capture_shapes` result, decide whether the capture trees fit the
    per-chip budget. On a frame-sharded mesh the maps shard over frames /
    spatial positions, so each chip holds 1/sp of the global bytes — which
    is exactly what makes the 24/32-frame long-video configs take the
    cached path on a slice while a single chip falls back to the live
    stream (cli/run_videop2p.py; VERDICT r4 item 5).

    Returns ``(use_cached, map_gb, per_chip_gb)``.
    """
    from videop2p_tpu.pipelines.cached import tree_bytes

    map_gb = tree_bytes(
        (cached_shapes.cross_maps, cached_shapes.temporal_maps)
    ) / 2**30
    per_chip_gb = map_gb / max(int(sp), 1)
    return per_chip_gb <= budget_gb, map_gb, per_chip_gb


def capture_shapes(
    unet_fn: UNetFn,
    params,
    scheduler: DDIMScheduler,
    latents,
    cond_src,
    ctx: Optional[ControlContext],
    *,
    num_inference_steps: int = 50,
    cross_len: int = 0,
    self_window: Tuple[int, int] = (0, 0),
    dependent_weight: float = 0.0,
    dependent_sampler: Optional[DependentNoiseSampler] = None,
    temporal_maps_dtype=None,
):
    """``eval_shape`` of the EXACT capture :func:`cached_fast_edit` will run
    — for HBM budgeting (cli/run_videop2p.py). Sharing the call site means a
    change to the fused program's capture cannot desynchronize the budget
    check that gates it. Returns the (trajectory, CachedSource) shape tree.
    """
    return jax.eval_shape(
        lambda p, x, k: ddim_inversion_captured(
            unet_fn, p, scheduler, x, cond_src,
            num_inference_steps=num_inference_steps,
            cross_len=cross_len,
            self_window=self_window,
            capture_blend=ctx is not None and ctx.blend is not None,
            dependent_weight=dependent_weight,
            dependent_sampler=dependent_sampler,
            key=k,
            temporal_maps_dtype=temporal_maps_dtype,
        ),
        params, latents, jax.random.key(0),
    )


def cached_fast_edit(
    unet_fn: UNetFn,
    params,
    scheduler: DDIMScheduler,
    latents: jax.Array,
    cond_src: jax.Array,
    cond_all: jax.Array,
    uncond: jax.Array,
    ctx: Optional[ControlContext],
    *,
    num_inference_steps: int = 50,
    guidance_scale: float = 7.5,
    cross_len: int = 0,
    self_window: Tuple[int, int] = (0, 0),
    dependent_weight: float = 0.0,
    dependent_sampler: Optional[DependentNoiseSampler] = None,
    key: Optional[jax.Array] = None,
    temporal_maps_dtype=None,
    telemetry: bool = False,
    device_probe: Optional[Callable] = None,
    attn_maps: bool = False,
    reuse_schedule: Optional[str] = None,
    student_head: Optional[dict] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Capture-inversion of ``latents`` under ``cond_src`` followed by the
    cached-source controlled edit under ``cond_all``/``uncond``. Returns
    ``(trajectory, edited_latents)`` — the trajectory for persistence, the
    (P, F, h, w, C) output with stream 0 the exact reconstruction.
    ``telemetry=True`` adds the edit scan's per-step telemetry
    (sampling.edit_sample) riding the same fused program; ``device_probe``
    (obs.comm.make_device_probe) adds per-device stats + cross-replica
    divergence of the edit scan's latents the same way; ``attn_maps=True``
    adds the attention observability capture (obs.attention) as
    ``{"inversion": ..., "edit": ...}`` — the source stream's heatmaps from
    the inversion walk plus the edit streams' heatmaps / entropies / blend
    mask series. Return order ``(trajectory, edited[, tel][, dev][, attn])``;
    all off by default, leaving the program byte-identical.
    ``reuse_schedule`` enables cross-step deep-feature reuse in the edit
    scan (pipelines/reuse.py) — the inversion capture always runs the full
    UNet (its maps feed the controllers); "off"/None is pinned
    byte-identical. ``student_head`` runs the edit scan as the
    consistency-distilled student (train/distill.py) — the inversion
    capture stays the TEACHER's (its maps and trajectory feed the
    controllers and the exact source replay); None is pinned
    byte-identical."""
    inv = ddim_inversion_captured(
        unet_fn, params, scheduler, latents, cond_src,
        num_inference_steps=num_inference_steps,
        cross_len=cross_len,
        self_window=self_window,
        capture_blend=ctx is not None and ctx.blend is not None,
        dependent_weight=dependent_weight,
        dependent_sampler=dependent_sampler,
        key=key,
        temporal_maps_dtype=temporal_maps_dtype,
        attn_maps=attn_maps,
    )
    trajectory, cached = inv[0], inv[1]
    edited = edit_sample(
        unet_fn, params, scheduler, trajectory[-1], cond_all, uncond,
        num_inference_steps=num_inference_steps,
        guidance_scale=guidance_scale,
        ctx=ctx,
        source_uses_cfg=False,
        cached_source=cached,
        telemetry=telemetry,
        device_probe=device_probe,
        attn_maps=attn_maps,
        reuse_schedule=reuse_schedule,
        student_head=student_head,
    )
    if not (telemetry or device_probe is not None or attn_maps):
        return trajectory, edited
    edited, *extras = edited
    out = (trajectory, edited)
    if telemetry:
        out += (extras.pop(0),)
    if device_probe is not None:
        out += (extras.pop(0),)
    if attn_maps:
        out += ({"inversion": inv[2], "edit": extras.pop(0)},)
    return out
