"""Attention-store plumbing between the UNet's sown maps and the control layer.

The reference's ``AttentionStore`` keeps per-step lists keyed
``{down,mid,up}_{cross,self}`` and LocalBlend consumes
``down_cross[2:4] + up_cross[:3]`` — exactly the cross-attention sites whose
query grid is (latent/4)² (run_videop2p.py:145, 251-268; SURVEY §3.4). Here the
UNet sows head-averaged maps into a flax collection; these helpers select the
blend sites by that resolution rule and stack them into the fixed-shape
``(P, F, S, r, r, L)`` tensor ``local_blend`` expects, so the running sum can
live in a ``lax.scan`` carry.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp

__all__ = ["blend_maps_from_store", "flatten_store"]


def flatten_store(store: Dict[str, Any]) -> List[Tuple[str, jax.Array]]:
    """(path, leaf) pairs in deterministic tree order. Each leaf is a sown
    head-mean probability map: cross sites (B·F, Q, L); temporal sites
    (B·N, F, F)."""
    flat = jax.tree_util.tree_flatten_with_path(store)[0]
    return [(jax.tree_util.keystr(path), leaf) for path, leaf in flat]


def _select_blend_leaves(
    store: Dict[str, Any], blend_res: Tuple[int, int], text_len: int
) -> List[jax.Array]:
    q_blend = blend_res[0] * blend_res[1]
    out = []
    for path, leaf in flatten_store(store):
        # head-mean store leaves are 3-d (B·F, Q, L); full-head capture leaves
        # in the attn_base collection are 4-d — exclude those
        if (
            "attn2" in path
            and leaf.ndim == 3
            and leaf.shape[-1] == text_len
            and leaf.shape[-2] == q_blend
        ):
            out.append(leaf)
    return out


def _cross_site_sizes(store: Dict[str, Any], text_len: int) -> List[int]:
    return sorted({
        leaf.shape[-2]
        for path, leaf in flatten_store(store)
        if "attn2" in path and leaf.ndim == 3 and leaf.shape[-1] == text_len
    })


def blend_maps_from_store(
    store: Dict[str, Any],
    *,
    latent_hw: Tuple[int, int],
    video_length: int,
    num_prompts: int,
    text_len: int,
    blend_res: Tuple[int, int] | None = None,
    num_uncond: int = -1,
) -> jax.Array:
    """Stack the blend-site cross maps into (P, F, S, r, r, L).

    Blend sites are the cross-attention layers at (latent/4)² queries — the
    16×16 maps for a 64² latent, generalizing the reference's hard-coded
    ``reshape(2, -1, 8, 16, 16, 77)`` (run_videop2p.py:146) to any latent size
    and frame count. Only the conditional streams are kept, matching the
    store's conditional-half rule (run_videop2p.py:217-218); ``num_uncond``
    counts the uncond streams ahead of them (-1 → ``num_prompts``, the
    symmetric CFG layout; fast mode runs with ``num_prompts − 1``).
    """
    r = blend_res if blend_res is not None else (latent_hw[0] // 4, latent_hw[1] // 4)
    U = num_prompts if num_uncond < 0 else num_uncond
    leaves = _select_blend_leaves(store, r, text_len)
    if not leaves and blend_res is None and latent_hw[0] == latent_hw[1]:
        # the (latent/4)² rule generalizes the reference's hard-coded 16×16
        # (run_videop2p.py:146) but small/tiny UNets may have no site at that
        # grid — fall back to the nearest square cross-site resolution
        # (trace-time selection on concrete shapes)
        sizes = _cross_site_sizes(store, text_len)
        target = r[0] * r[1]
        squares = [q for q in sizes if int(q ** 0.5) ** 2 == q]
        if squares:
            q = min(squares, key=lambda s: abs(s - target))
            side = int(q ** 0.5)
            r = (side, side)
            leaves = _select_blend_leaves(store, r, text_len)
    if not leaves:
        raise ValueError(
            f"no cross-attention maps at blend resolution {r} in store "
            f"(text_len={text_len}, available query sizes "
            f"{_cross_site_sizes(store, text_len)}) — latent_hw mismatch?"
        )
    stacked = jnp.stack(leaves, axis=1)  # ((U+P)·F, S, Q, L)
    _, s, q, L = stacked.shape
    stacked = stacked.reshape(U + num_prompts, video_length, s, r[0], r[1], L)
    return stacked[U:]  # conditional streams → (P, F, S, r, r, L)
