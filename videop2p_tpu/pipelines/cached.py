"""Cached-source fast editing: replay the source stream from inversion.

The reference's fast mode keeps the source stream in the CFG batch and
re-predicts its ε from the drifting latent every step
(/root/reference/tuneavideo/pipelines/pipeline_tuneavideo.py:412-415) — one
full UNet stream spent on an *approximate* replay of the DDIM inversion.
Here the replay is free and exact: DDIM ``next_step``/``prev_step`` are
linear in (x, ε) with identical coefficients, so the source latent at edit
step *i* IS ``trajectory[N−i]`` — no forward needed. The edit batch drops
from (P−1)+P to (P−1)+(P−1) streams (33 % fewer UNet streams at P=2).

What the dropped stream used to provide, and where it comes from now:

  * its ε — unnecessary: the latent path is read straight off the reversed
    inversion trajectory (exact where the reference drifts);
  * base attention maps for the controllers — captured during inversion
    (``attn_base`` collection, full per-head probs) at the steps that need
    them. The cross gate ``cross_replace_alpha[i]`` is zero past its window
    and the temporal gate is a [lo, hi) step window
    (run_videop2p.py:304-317) — outside the windows the edited output equals
    the unedited edit-stream maps, so capturing ONLY the gated steps is
    semantically exact and is what keeps the cache inside HBM (rabbit-jump:
    ~3 GB vs ~13 GB for all 50 steps);
  * its LocalBlend store contribution — captured per step as the already
    head-meaned, blend-site-stacked tensor (tiny).

One disclosed approximation: the captured maps come from the inversion
forward at ``(trajectory[j], t_j)`` while a live source stream would compute
them at ``(trajectory[j+1], t_j)`` — the same timestep, one trajectory
position earlier. The latent replay itself is exact; only the controllers'
*base maps* carry this one-position offset (they are semantic layout guides,
and the reference's own fast mode feeds the controllers maps from a drifted
latent).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import struct

__all__ = [
    "CachedSource",
    "capture_windows",
    "check_subset_windows",
    "filter_site_tree",
    "merge_site_trees",
    "slice_site_tree",
    "tree_bytes",
    "validate_step_positions",
]


def capture_windows(ctx, num_steps: int) -> Tuple[int, Tuple[int, int]]:
    """The gate rule that decides which inversion steps must capture maps:
    cross base maps are only read while ANY word's ``cross_replace_alpha`` is
    nonzero (a step prefix — conservative for per-word dict schedules), and
    temporal base maps only inside the self-replace window. Returns
    ``(cross_len, (self_lo, self_hi))``. Shared by the CLI, the bench and
    the tests so the rule cannot drift between them."""
    import numpy as np

    cra = np.asarray(jax.device_get(ctx.cross_replace_alpha))[:num_steps]
    active = (cra != 0).any(axis=tuple(range(1, cra.ndim)))
    cross_len = int(active.nonzero()[0].max()) + 1 if active.any() else 0
    return cross_len, ctx.self_replace_range


def validate_step_positions(positions, base_steps: int):
    """Normalize/validate a timestep-subset walk's positions into the
    ``base_steps`` edit-order grid (``DDIMScheduler.subset_positions`` is
    the canonical producer). Strictly increasing, starting at 0 (the
    subset walk must begin at the same x_T the capture did), ending inside
    the base grid. Returns an int64 numpy array."""
    import numpy as np

    pos = np.asarray(positions, dtype=np.int64)
    if pos.ndim != 1 or pos.size < 1:
        raise ValueError(f"step_positions must be a 1-D sequence, got {positions!r}")
    if pos[0] != 0:
        raise ValueError(
            f"step_positions must start at 0 (the capture's x_T), got {pos[0]}"
        )
    if pos.size > 1 and (np.diff(pos) <= 0).any():
        raise ValueError(f"step_positions must be strictly increasing: {pos.tolist()}")
    if pos[-1] >= base_steps:
        raise ValueError(
            f"step_positions reach {pos[-1]} but the capture covers "
            f"[0, {base_steps})"
        )
    return pos


def check_subset_windows(ctx, cached, positions, num_steps: int) -> None:
    """Host-side gate-coverage check for a timestep-subset edit over a
    ``cached`` capture: every subset step whose controller gate is OPEN
    must map (via ``positions``) inside the captured base window — a step
    outside it would silently read a clamped/stale base map. Requires a
    CONCRETE controller (call before tracing; the serving layer does)."""
    import numpy as np

    if ctx is None or ctx.kind == "empty":
        return
    cross_len_sub, (lo_s, hi_s) = capture_windows(ctx, num_steps)
    pos = np.asarray(positions)
    if cross_len_sub > 0:
        mapped = pos[:cross_len_sub]
        if cached.cross_len <= 0 or int(mapped.max()) >= cached.cross_len:
            raise ValueError(
                f"subset cross window maps to base steps {mapped.tolist()} "
                f"outside the captured cross window [0, {cached.cross_len})"
            )
    if hi_s > lo_s:
        mapped = pos[lo_s:hi_s]
        lo_b, hi_b = cached.self_window
        if mapped.size and (int(mapped.min()) < lo_b or int(mapped.max()) >= hi_b):
            raise ValueError(
                f"subset self window maps to base steps {mapped.tolist()} "
                f"outside the captured self window [{lo_b}, {hi_b})"
            )


def filter_site_tree(tree: Dict[str, Any], site_name: str) -> Dict[str, Any]:
    """Keep only the subtrees whose path ends at a module named ``site_name``
    (``"attn2"`` for cross sites, ``"attn_temp"`` for temporal sites)."""
    out: Dict[str, Any] = {}
    for k, v in tree.items():
        if k == site_name:
            out[k] = v
        elif isinstance(v, dict):
            sub = filter_site_tree(v, site_name)
            if sub:
                out[k] = sub
    return out


def merge_site_trees(a: Optional[Dict[str, Any]], b: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    """Deep-merge two nested site trees with disjoint leaves."""
    if not a:
        return dict(b or {})
    if not b:
        return dict(a)
    out = dict(a)
    for k, v in b.items():
        if k in out and isinstance(out[k], dict) and isinstance(v, dict):
            out[k] = merge_site_trees(out[k], v)
        else:
            out[k] = v
    return out


def slice_site_tree(tree: Optional[Dict[str, Any]], index: jax.Array) -> Optional[Dict[str, Any]]:
    """Index every leaf's leading (step-window) axis at a traced index."""
    if not tree:
        return None
    return jax.tree.map(
        lambda a: jax.lax.dynamic_index_in_dim(a, index, axis=0, keepdims=False), tree
    )


def tree_bytes(tree) -> int:
    """Total bytes of the array (or ShapeDtypeStruct) leaves of a pytree."""
    import math

    return sum(
        math.prod(leaf.shape) * leaf.dtype.itemsize
        for leaf in jax.tree.leaves(tree)
        if hasattr(leaf, "dtype") and hasattr(leaf, "shape")
    )


class CachedSource(struct.PyTreeNode):
    """Everything the cached-source edit scan reads in place of a live source
    stream. All step-indexed arrays are in EDIT-step order (the reverse of
    the inversion walk that produced them).
    """

    # (num_steps+1, 1, F, h, w, C) — reversed trajectory: [i] is the source
    # latent entering edit step i; [i+1] the latent after it; [-1] is x_0
    src_latents: jax.Array
    # nested {path: {"probs": (cross_len, F, H, Q, W)}} for attn2 sites,
    # covering edit steps [0, cross_len); None/{} when no cross edit
    cross_maps: Optional[Dict[str, Any]] = None
    # nested {path: {"probs": (hi−lo, D, H, F, F)}} for attn_temp sites,
    # covering edit steps [lo, hi); None/{} when no temporal edit
    temporal_maps: Optional[Dict[str, Any]] = None
    # (num_steps, 1, F, S, r, r, L) — the source stream's per-step LocalBlend
    # store contribution; None when no blend is configured
    blend_seq: Optional[jax.Array] = None

    # step windows the maps cover (static)
    cross_len: int = struct.field(pytree_node=False, default=0)
    self_window: Tuple[int, int] = struct.field(pytree_node=False, default=(0, 0))

    def _capture_compute_dtype(self):
        """The dtype the capture's full-precision maps carry — the upcast
        target for float8-stored temporal maps. Sibling cross maps first
        (same capture forward, same probability compute dtype), then the
        blend sequence; float32 when every wide sibling was elided (a
        temporal-only capture declares no other precision)."""
        for tree in (self.cross_maps, self.blend_seq):
            for leaf in jax.tree.leaves(tree):
                if (
                    hasattr(leaf, "dtype")
                    and jnp.dtype(leaf.dtype).itemsize > 1
                ):
                    return leaf.dtype
        return jnp.float32

    def base_tree_at(self, step_index: jax.Array) -> Optional[Dict[str, Any]]:
        """Per-step base-map tree for :class:`AttnControl.cached_base`.

        Outside a window the slice index clamps to the window edge — the
        stale value is provably unused because the corresponding gate
        (cross_replace_alpha / the self-replace window) multiplies it out.
        """
        cross = None
        if self.cross_maps and self.cross_len > 0:
            idx = jnp.clip(step_index, 0, self.cross_len - 1)
            cross = slice_site_tree(self.cross_maps, idx)
        temporal = None
        lo, hi = self.self_window
        if self.temporal_maps and hi > lo:
            idx = jnp.clip(step_index - lo, 0, hi - lo - 1)
            temporal = slice_site_tree(self.temporal_maps, idx)
            # maps may be STORED in a narrow float8 (the long-video budget
            # mode, inversion.py temporal_maps_dtype) — upcast at read to
            # the dtype the sibling captured maps carry (the capture's
            # probability compute dtype), NOT a hardcoded bf16: in an fp32
            # run a bf16 upcast would silently narrow the replaced base
            # maps while the cross maps stay fp32
            target = self._capture_compute_dtype()

            def _widen(a):
                dt = jnp.dtype(a.dtype)
                if dt.itemsize != 1:
                    return a
                if jnp.issubdtype(dt, jnp.integer):
                    # int8 fixed-point storage (inversion.py encodes
                    # round(p·127)) — decode, not just upcast
                    return a.astype(target) / jnp.asarray(127.0, target)
                return a.astype(target)

            temporal = jax.tree.map(_widen, temporal)
        if cross is None and temporal is None:
            return None
        return merge_site_trees(cross, temporal)

    @property
    def num_steps(self) -> int:
        return self.src_latents.shape[0] - 1
