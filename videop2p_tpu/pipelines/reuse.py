"""Cross-step deep-feature reuse schedules (ISSUE 15, DeepCache-style).

Adjacent diffusion steps recompute nearly identical deep UNet features
(Ma et al., 2023): on designated "skip" steps the deep down/mid/up stages
can be skipped entirely and the cached deep feature — the input to the
FINAL up block, carried in the fused scan's state the same
zero-extra-dispatch way obs telemetry rides it — reused, so only the
shallow path (conv_in → down block 0 → final up block → out convs) runs.
The schedule is STATIC: it becomes a per-step boolean in the scan's xs
and a ``lax.cond`` in the scan body, so the whole edit stays ONE compiled
program regardless of K.

Grammar (the ``reuse_schedule`` knob):
  * ``"off"``          — no reuse; the scan body is byte-identical (pinned).
  * ``"uniform:K"``    — full UNet every K-th step (positions 0, K, 2K, …),
    shallow in between; skip fraction (K-1)/K.
  * ``"custom:<p0,p1,...>"`` — explicit full-step positions, validated the
    way ``validate_step_positions`` validates timestep subsets: strictly
    increasing, starting at 0 (the first step must prime the cache), all
    inside ``[0, num_steps)``.

Stdlib only.
"""

from __future__ import annotations

from typing import Optional, Tuple

__all__ = [
    "REUSE_OFF",
    "parse_reuse_schedule",
    "validate_reuse_schedule",
    "reuse_skip_fraction",
    "reuse_label",
]

REUSE_OFF = "off"


def parse_reuse_schedule(schedule: Optional[str],
                         num_steps: int) -> Optional[Tuple[bool, ...]]:
    """A schedule string → per-step full-UNet flags (length ``num_steps``,
    ``True`` = run the full UNet, ``False`` = shallow reuse step), or None
    for "off". Raises ``ValueError`` on malformed schedules, mirroring
    ``pipelines.cached.validate_step_positions``'s contract: position 0
    must be a full step — there is no cached deep feature to reuse yet."""
    if schedule in (None, REUSE_OFF, ""):
        return None
    schedule = str(schedule)
    num_steps = int(num_steps)
    if num_steps < 1:
        raise ValueError(f"num_steps must be >= 1, got {num_steps}")
    if schedule.startswith("uniform:"):
        try:
            k = int(schedule.split(":", 1)[1])
        except ValueError:
            raise ValueError(
                f"reuse_schedule={schedule!r}: uniform:K needs an integer K"
            ) from None
        if k < 1:
            raise ValueError(
                f"reuse_schedule={schedule!r}: K must be >= 1 "
                "(K=1 runs the full UNet every step)"
            )
        return tuple(i % k == 0 for i in range(num_steps))
    if schedule.startswith("custom:"):
        body = schedule.split(":", 1)[1]
        try:
            positions = tuple(int(p) for p in body.split(",") if p.strip())
        except ValueError:
            raise ValueError(
                f"reuse_schedule={schedule!r}: custom:<positions> needs a "
                "comma-separated integer list"
            ) from None
        if not positions:
            raise ValueError(
                f"reuse_schedule={schedule!r}: custom needs at least one "
                "full-step position"
            )
        if positions[0] != 0:
            raise ValueError(
                f"reuse_schedule={schedule!r}: positions must start at 0 — "
                "the first step has no cached deep feature to reuse"
            )
        if any(b <= a for a, b in zip(positions, positions[1:])):
            raise ValueError(
                f"reuse_schedule={schedule!r}: positions must be strictly "
                "increasing"
            )
        if positions[-1] >= num_steps:
            raise ValueError(
                f"reuse_schedule={schedule!r}: position {positions[-1]} is "
                f"outside [0, {num_steps}) for this step count"
            )
        full = [False] * num_steps
        for p in positions:
            full[p] = True
        return tuple(full)
    raise ValueError(
        f"reuse_schedule={schedule!r} is not 'off', 'uniform:K' or "
        "'custom:<p0,p1,...>'"
    )


def validate_reuse_schedule(schedule: Optional[str], num_steps: int) -> str:
    """Validate and normalize a schedule knob value (None/"" → "off");
    returns the canonical string. The cheap fail-fast entry serve
    admission and ProgramSpec construction share."""
    if schedule in (None, "", REUSE_OFF):
        return REUSE_OFF
    parse_reuse_schedule(schedule, num_steps)
    return str(schedule)


def reuse_skip_fraction(full_flags: Optional[Tuple[bool, ...]]) -> float:
    """Fraction of steps that run the shallow path (0.0 when off) — the
    number the per-step flop drop in the cost capture is checked against."""
    if not full_flags:
        return 0.0
    return 1.0 - (sum(1 for f in full_flags if f) / float(len(full_flags)))


def reuse_label(schedule: Optional[str]) -> str:
    """A program-label-safe suffix token for a schedule
    (``uniform:2`` → ``uniform2``; off → "")."""
    if schedule in (None, "", REUSE_OFF):
        return ""
    return str(schedule).replace(":", "").replace(",", "_")
